"""Trace harness: analyze_trace of a 1k-call plain ``.remote()`` burst.

Companion to bench_core.py's throughput rows — this answers *where the
time goes* for a naive submit loop, per the trace-first rule in
ROADMAP.md. Runs with runtime tracing forced on, wraps the burst in one
user span so every call stitches into a single trace, then feeds the
collected spans through util.tracing.analyze_trace and prints the
stage breakdown as JSON. The before/after artifacts live in
TRACE_pr18.md.

Usage:  JAX_PLATFORMS=cpu python trace_burst.py [n_calls]
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["RAY_TPU_TRACING"] = "1"

import ray_tpu
from ray_tpu.util import tracing


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    ray_tpu.init(num_cpus=2, max_workers=2)
    try:
        from ray_tpu._private import worker

        hub = worker._hub
        if hub is not None:
            # the default 1024-span-per-trace cap would truncate a
            # 1k-call burst's ~4k spans and bias the stage shares
            # toward whatever finishes first
            hub._trace_span_max = 65536
        client = worker.get_client()

        @ray_tpu.remote
        def noop(i):
            return i

        # warmup outside the trace: worker spawn + function registration
        # are one-time costs, not part of the steady-state submit path
        ray_tpu.get([noop.remote(i) for i in range(20)], timeout=60)

        with tracing.span("burst"):
            ctx = tracing.current_context()
            refs = [noop.remote(i) for i in range(n)]
            ray_tpu.get(refs, timeout=180)
        trace_id = ctx[0]

        # spans land at the hub asynchronously; poll until the count
        # stops growing
        prev = -1
        spans = []
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            spans = client.list_state("traces", trace_id=trace_id)
            if spans and len(spans) == prev:
                break
            prev = len(spans)
            time.sleep(0.5)

        analysis = tracing.analyze_trace(spans)
        json.dump(analysis, sys.stdout, indent=2)
        print()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
