"""Podracer throughput benchmarks (rllib/podracer).

Measures end-to-end RL steps/s for both Podracer layouts on the local
backend, in bench_core conventions — one JSON line per row:

    {"metric": ..., "value": N, "unit": ..., "platform": ..., "vs_baseline": N}

Rows:
- anakin_steps_per_sec: the co-jitted env+learner loop driven through
  the compiled-DAG resident exec loop (steady state: compile excluded
  by a warmup tick, each trial re-ticks the same resident worker).
- sebulba_steps_per_sec: the actor/learner split — bulk-submitted
  fragment fan-out, shm object-plane trajectory hand-off, sharded
  learner with collective-group all-reduce, KV param broadcast.
  Includes the pipeline's real coupling costs (first trial carries the
  worker-side jit compile; prefer --trials medians).

Baselines are cpu-box numbers (JAX_PLATFORMS=cpu, 8 virtual devices)
measured on this repo's CI box at the rows' introduction (PR 20).
Every row is stamped with the detected platform; vs_baseline is
refused (null) off the baseline platform — a TPU run of these rows
must establish its own MULTICHIP baseline, never ratio against cpu.

MULTICHIP status: on a non-cpu backend this harness still runs both
layouts against the local chips, but the cross-slice topology (SLICE
placement, per-slice gangs, ICI all-reduce) is a stub until a live
multi-chip TPU session exists — the run emits a podracer_multichip
note row instead of silently reporting one-chip numbers as MULTICHIP.

Run: python bench_podracer.py [--quick] [--smoke] [--trials N] [--json PATH]
(flag semantics identical to bench_core.py; smoke numbers are NOT
comparable, they exist for tests/test_bench_podracer.py)
"""

from __future__ import annotations

import json
import sys

import numpy as np

from bench_core import _detect_platform, _parse_argv as _core_parse

BASELINES = {
    # cpu-box numbers, --quick --trials 3 medians at introduction
    "anakin_steps_per_sec": 61900.0,
    "sebulba_steps_per_sec": 29700.0,
}

BASELINE_PLATFORM = "cpu"

SMOKE = False
QUICK = False
TRIALS = None
JSON_PATH = None
RESULTS = []


def _parse_argv(argv) -> None:
    """bench_core's flag grammar, landed into this module's globals."""
    global SMOKE, QUICK, TRIALS, JSON_PATH
    import bench_core

    _core_parse(argv)
    SMOKE, QUICK = bench_core.SMOKE, bench_core.QUICK
    TRIALS, JSON_PATH = bench_core.TRIALS, bench_core.JSON_PATH


def report(metric: str, value, unit: str) -> None:
    trials_list = None
    if isinstance(value, list):  # --trials mode: per-trial samples
        trials_list = [round(v, 3) for v in value]
        value = float(np.median(value))
    platform = _detect_platform()
    base = BASELINES.get(metric)
    if platform != BASELINE_PLATFORM:
        ratio = None  # cpu baselines: never ratio across hardware
    elif base:
        ratio = value / base
    else:
        ratio = None
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "platform": platform,
        "vs_baseline": round(ratio, 3) if ratio else None,
    }
    if trials_list is not None:
        rec["trials"] = trials_list
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def _multichip_stub(platform: str) -> None:
    print(json.dumps({
        "metric": "podracer_multichip",
        "value": None,
        "unit": "note",
        "platform": platform,
        "note": (
            "MULTICHIP topology (SLICE-placed per-slice gangs, ICI "
            "all-reduce) is stubbed: this run measured the local "
            f"{platform} devices only. Rows above carry "
            "vs_baseline=null — establish a MULTICHIP baseline before "
            "comparing."
        ),
    }), flush=True)


def _anakin_steps_per_sec():
    from ray_tpu.rllib.podracer import PodracerConfig

    if SMOKE:
        num_envs, frag, supersteps, ticks = 16, 8, 1, 3
    elif QUICK:
        num_envs, frag, supersteps, ticks = 64, 16, 2, 10
    else:
        num_envs, frag, supersteps, ticks = 64, 16, 2, 40
    driver = (
        PodracerConfig()
        .environment("CartPole-v1")
        .podracer(
            mode="anakin", num_envs=num_envs,
            anakin_supersteps_per_call=supersteps,
        )
        .env_runners(rollout_fragment_length=frag)
        .debugging(seed=0)
        .build()
    )
    try:
        driver.train(num_ticks=1)  # compile + channel warmup
        samples = [
            driver.train(num_ticks=ticks)["steps_per_sec"]
            for _ in range(TRIALS or 1)
        ]
    finally:
        driver.stop()
    return samples if TRIALS else samples[0]


def _sebulba_steps_per_sec():
    from ray_tpu.rllib.podracer import PodracerConfig

    if SMOKE:
        actors, envs, frag, shards, rounds = 2, 8, 8, 1, 3
    elif QUICK:
        actors, envs, frag, shards, rounds = 2, 16, 32, 2, 8
    else:
        actors, envs, frag, shards, rounds = 2, 16, 32, 2, 24
    driver = (
        PodracerConfig()
        .environment("CartPole-v1")
        .podracer(
            mode="sebulba", learner_shards=shards,
            max_inflight_rounds=2, namespace="bench",
        )
        .env_runners(
            num_actors=actors, envs_per_actor=envs,
            rollout_fragment_length=frag,
        )
        .debugging(seed=0)
        .build()
    )
    try:
        driver.train(num_rounds=1)  # actor+learner jit compile round
        samples = [
            driver.train(num_rounds=rounds)["steps_per_sec"]
            for _ in range(TRIALS or 1)
        ]
    finally:
        driver.stop()
    return samples if TRIALS else samples[0]


def main() -> None:
    import os

    # CPU-benchable SPMD: both layouts shard over multiple devices
    # (anakin's mesh, sebulba's learner group), so a cpu run needs the
    # virtual-device split tests/conftest.py uses — set BEFORE any jax
    # backend init so the driver and every spawned worker inherit it
    if (
        _detect_platform() == "cpu"
        and "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )

    import ray_tpu

    ray_tpu.init(num_cpus=8, max_workers=4 if SMOKE else 8)
    try:
        report("anakin_steps_per_sec", _anakin_steps_per_sec(), "steps/s")
        report("sebulba_steps_per_sec", _sebulba_steps_per_sec(), "steps/s")
    finally:
        ray_tpu.shutdown()

    platform = _detect_platform()
    if platform != BASELINE_PLATFORM:
        _multichip_stub(platform)

    ratios = [r["vs_baseline"] for r in RESULTS
              if r["vs_baseline"] and r.get("platform") == BASELINE_PLATFORM]
    geomean = float(np.exp(np.mean(np.log(ratios)))) if ratios else 0.0
    summary = {
        "metric": "podracer_bench_geomean_vs_baseline",
        "value": round(geomean, 3),
        "unit": "ratio",
        "platform": platform,
        "vs_baseline": round(geomean, 3),
        "detail": {r["metric"]: r["value"] for r in RESULTS},
    }
    print(json.dumps(summary))
    if JSON_PATH:
        with open(JSON_PATH, "w") as f:
            json.dump(
                {
                    "mode": "smoke" if SMOKE else ("quick" if QUICK else "full"),
                    "trials": TRIALS or 1,
                    "platform": platform,
                    "metrics": {r["metric"]: r for r in RESULTS},
                    "geomean_vs_baseline": round(geomean, 3),
                },
                f, indent=2,
            )
            f.write("\n")


if __name__ == "__main__":
    _parse_argv(sys.argv[1:])
    main()
