"""Multi-tenant scheduling policy: priority, fair share, quotas.

The hub's dispatch layer is single-tenant FIFO: runnable tasks queue
per scheduling class and classes are visited in insertion order, so one
greedy driver can starve every other client of TPU chips indefinitely.
This module is the policy engine that sits between submission and that
per-class dispatch (the shape multi-tenant accelerator clusters need —
"On Scheduling Ring-All-Reduce Learning Jobs in Multi-Tenant GPU
Clusters", arxiv 2207.07817):

- **Jobs / tenants**: every driver (or submitted job) may register a
  ``JobEntry`` — tenant id, integer priority, optional resource quota —
  at ``init(job_config=...)`` / ``job submit`` time. The registry is
  pruned when the registering connection goes away (graftlint GL009
  guards hub-side registries against unpruned growth).
- **Ordering**: runnable scheduling classes are ordered by
  ``(-priority, weighted fair-share usage)`` instead of raw FIFO.
  Fair-share usage is accumulated work-seconds (chips, else CPUs, of
  dispatched tasks x wall time, from an injectable clock so tests are
  deterministic), normalized by the tenant's quota weight — the tenant
  furthest below its share dispatches first.
- **Quotas**: enforced at admission. A task that would push its
  tenant's admitted usage over quota parks in a per-tenant
  ``pending_quota`` queue instead of entering the runnable set (so it
  is invisible to the autoscaler's demand view), and is re-admitted as
  soon as finishing work frees room.
- **Preemption** (policy half): when a higher-priority job's placement
  group / SLICE reservation cannot fit, :meth:`preemption_victims`
  selects victim gangs — whole placement groups or single running
  tasks, lowest priority first, never partial gangs. The hub executes
  the kill through the existing retry/restart machinery so preempted
  work requeues with lineage intact (gang scheduling makes preemption
  the only way to reclaim a contiguous ICI slice — "Podracer
  architectures for scalable RL", arxiv 2104.06272).

Everything here runs on the hub's reactor thread: no locks, and the
whole module stays inert (O(1) no-ops on the hot path) until the first
job/tenant registers.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

DEFAULT_TENANT = "default"

# fair-share usage half-life: consumption this old counts half. Bounds
# how long historical usage can bias the deficit ordering against a
# tenant (and, with the entry baseline in _tenant(), how long a
# newcomer's advantage lasts).
USAGE_HALFLIFE_S = 600.0


class QuotaInfeasibleError(Exception):
    """The task's resource request exceeds its tenant's quota outright —
    it could never be admitted even on a fully idle tenant. Raised at
    admission so the submit fails loudly instead of parking forever
    (and wedging the tenant's FIFO pending_quota queue behind it)."""


@dataclass
class JobEntry:
    """One registered driver/job (the hub-side registry row)."""

    job_id: str
    tenant: str = DEFAULT_TENANT
    priority: int = 0
    quota: Dict[str, float] = field(default_factory=dict)
    # id(conn) of the registering connection; the registry is pruned in
    # the hub's disconnect path keyed on this (GL009: a message-handler
    # registry must have a cleanup edge)
    conn_id: Optional[int] = None
    submitted: int = 0
    dispatched: int = 0
    preempted: int = 0


@dataclass
class TenantEntry:
    """Aggregate accounting per tenant (quota + fair-share state)."""

    name: str
    # resource caps; empty = unlimited. Units: the hub's resource units
    # (whole TPU chips, CPU cores, bytes of "memory").
    quota: Dict[str, float] = field(default_factory=dict)
    # admitted-but-not-finished usage (charged at admission, released
    # at final task completion / permanent actor death)
    admitted: Dict[str, float] = field(default_factory=dict)
    # fair-share clock: accumulated work-seconds of dispatched tasks.
    # `rate` is the current aggregate work of running tasks; usage_s is
    # folded forward from rate_since whenever rate changes, so the live
    # value at time t is usage_s + rate * (t - rate_since) in O(1).
    usage_s: float = 0.0
    rate: float = 0.0
    rate_since: float = 0.0
    # tasks parked at admission because the tenant is over quota
    parked: Deque[Any] = field(default_factory=deque)
    n_preempted: int = 0

    def live_usage(self, now: float) -> float:
        """Accumulated usage with exponential decay (half-life
        USAGE_HALFLIFE_S): old consumption fades, so the deficit
        ordering reflects the recent past — a tenant that ran alone
        for an hour is not owed an hour of starvation once a
        competitor shows up."""
        dt = max(0.0, now - self.rate_since)
        decay = 0.5 ** (dt / USAGE_HALFLIFE_S) if dt > 0 else 1.0
        return self.usage_s * decay + self.rate * dt

    def weight(self) -> float:
        """Fair-share weight from the quota's primary resource (chips,
        else CPUs); quota-less tenants weigh 1.0 (equal share)."""
        w = self.quota.get("TPU") or self.quota.get("CPU") or 0.0
        return w if w > 0 else 1.0


def _work(resources: Dict[str, float]) -> float:
    """The scalar work rate a dispatched task charges its tenant's
    fair-share clock with: chips if it holds any, else CPUs, else a
    nominal 1.0 so zero-resource tasks still register."""
    return (
        resources.get("TPU", 0.0)
        or resources.get("CPU", 0.0)
        or 1.0
    )


class FairScheduler:
    """Policy engine owned by (and only touched from) the scheduler
    state service — the hub's state-plane thread (with the sharded
    control plane, hub_shards.py, reactor shards never call in here;
    they deliver messages over the rings and this engine runs behind
    them, so quota/priority ordering stays globally consistent no
    matter which shard a submit arrived on).

    ``clock`` is injectable for deterministic tests (defaults to
    ``time.monotonic``).
    """

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self.jobs: Dict[str, JobEntry] = {}
        self.tenants: Dict[str, TenantEntry] = {}
        # task_id -> (tenant, resources) quota charge, for idempotent
        # release (retries must not re-charge, double releases must not
        # under-count)
        self._admitted: Dict[bytes, Tuple[str, Dict[str, float]]] = {}
        # task_id -> (tenant, work) running fair-share interval
        self._running: Dict[bytes, Tuple[str, float]] = {}
        self.preemptions = 0
        # single-owner discipline: the state plane binds itself before
        # the first message and mutating entry points cheaply verify it
        # (a reactor shard mutating policy state is the GL010 bug class
        # — this is the runtime tripwire for the same invariant)
        self._owner_ident: Optional[int] = None

    def bind_owner(self) -> None:
        """Called by the owning thread (hub state plane) at loop start."""
        self._owner_ident = threading.get_ident()

    def _assert_owner(self) -> None:
        # sits on the per-submit admit() path once tenants exist: two
        # attribute loads and a compare, nothing heavier
        if (
            self._owner_ident is not None
            and threading.get_ident() != self._owner_ident
        ):
            raise RuntimeError(
                "FairScheduler mutated off its owner thread — state "
                "services are single-threaded; route through the "
                "shard ring instead (see hub_shards.py)"
            )

    # ------------------------------------------------------------ registry
    def active(self) -> bool:
        return bool(self.tenants)

    def register_job(
        self,
        job_id: str,
        tenant: str = DEFAULT_TENANT,
        priority: int = 0,
        quota: Optional[Dict[str, float]] = None,
        conn_id: Optional[int] = None,
    ) -> JobEntry:
        """``quota`` is tri-state: None = no opinion (the tenant's
        existing cap, if any, stands); a dict — INCLUDING the empty
        dict — is declared and wins (one quota per tenant, shared by
        all its jobs, last declaration wins; ``quota={}`` lifts an
        earlier cap)."""
        self._assert_owner()
        tenant = tenant or DEFAULT_TENANT
        entry = self.jobs.get(job_id)
        if entry is None:
            entry = self.jobs[job_id] = JobEntry(job_id=job_id)
        entry.tenant = tenant
        entry.priority = int(priority or 0)
        entry.quota = {
            k: float(v) for k, v in (quota or {}).items()
        }
        entry.conn_id = conn_id
        t = self._tenant(tenant)
        if quota is not None:
            t.quota = dict(entry.quota)
        return entry

    def drop_conn(self, conn_id: int) -> List[str]:
        """Prune jobs registered by a connection that went away. Tenant
        aggregates survive while they still hold admitted work or
        parked tasks (the accounting must outlive the registering
        socket); fully-idle tenants with no remaining jobs are dropped
        so the registry cannot grow without bound under client churn."""
        gone = [j for j, e in self.jobs.items() if e.conn_id == conn_id]
        for job_id in gone:
            del self.jobs[job_id]
        live_tenants = {e.tenant for e in self.jobs.values()}
        for name in [n for n in self.tenants if n not in live_tenants]:
            t = self.tenants[name]
            if not t.parked and not any(t.admitted.values()):
                del self.tenants[name]
        return gone

    def _tenant(self, name: str) -> TenantEntry:
        t = self.tenants.get(name)
        if t is None:
            now = self.clock()
            # entry baseline: a newcomer starts at the LOWEST incumbent
            # usage, not zero — otherwise it would monopolize contended
            # chips until it caught up with everyone's history
            base = min(
                (x.live_usage(now) for x in self.tenants.values()),
                default=0.0,
            )
            t = self.tenants[name] = TenantEntry(
                name=name, usage_s=base, rate_since=now
            )
        return t

    # --------------------------------------------------------- spec helpers
    @staticmethod
    def tenant_of(options: dict) -> str:
        return options.get("tenant") or DEFAULT_TENANT

    @staticmethod
    def priority_of(options: dict) -> int:
        try:
            return int(options.get("priority") or 0)
        except (TypeError, ValueError):
            return 0

    def _note_submit(self, options: dict) -> None:
        if options.get("_fs_counted"):
            return  # retries re-enter admit(); count each task once
        options["_fs_counted"] = True
        job = self.jobs.get(options.get("job_id") or "")
        if job is not None:
            job.submitted += 1

    # ------------------------------------------------------------ admission
    def admit(self, spec) -> bool:
        """Quota gate. True = runnable now; False = parked in the
        tenant's pending_quota queue (caller must not enqueue). Charges
        the tenant's admitted usage on success — idempotent per task,
        so retries re-admit for free."""
        if not self.tenants:
            return True  # no quotas/jobs registered: stay inert
        self._assert_owner()
        if spec.task_id in self._admitted:
            return True  # retry of already-admitted work
        tenant_name = self.tenant_of(spec.options)
        self._note_submit(spec.options)
        t = self.tenants.get(tenant_name)
        if t is None or not t.quota:
            return True  # unregistered or unlimited tenant
        infeasible = {
            k: cap for k, cap in t.quota.items()
            if spec.resources.get(k, 0.0) > cap + 1e-9
        }
        if infeasible:
            raise QuotaInfeasibleError(
                f"task requires {spec.resources} but tenant "
                f"'{tenant_name}' quota caps {infeasible} — it can never "
                "be admitted; shrink the request or raise the quota"
            )
        if spec.options.get("placement_group"):
            # PG-resident tasks draw from their gang's bundles, whose
            # capacity was already quota-charged when the reservation
            # was admitted (charge_reservation) — charging the task
            # too would double-count and wedge the tenant
            return True
        if t.parked or not self._fits_quota(t, spec.resources):
            # park behind any already-parked work even if THIS spec
            # would fit: re-admission is FIFO per tenant, and letting
            # fresh small tasks slip past a parked big one would starve
            # the queue head forever
            t.parked.append(spec)
            return False
        self._charge_admission(t, spec)
        return True

    def admit_many(self, specs) -> List[bool]:
        """One admission fold for a homogeneous batch (bulk submit:
        same fn, same resources, same tenant/options shape). Verdict
        per spec, same semantics as admit() called in order — but the
        inert-case check, owner assert, tenant lookup, and the
        infeasibility screen run ONCE per batch instead of once per
        task. Parking stays FIFO: the first spec that doesn't fit
        parks, and everything after it parks behind it."""
        if not self.tenants or not specs:
            return [True] * len(specs)
        self._assert_owner()
        head = specs[0]
        tenant_name = self.tenant_of(head.options)
        t = self.tenants.get(tenant_name)
        if t is not None and t.quota:
            # homogeneous resources: one infeasible spec means the
            # whole batch can never run — fail it in one raise
            infeasible = {
                k: cap for k, cap in t.quota.items()
                if head.resources.get(k, 0.0) > cap + 1e-9
            }
            if infeasible:
                raise QuotaInfeasibleError(
                    f"task requires {head.resources} but tenant "
                    f"'{tenant_name}' quota caps {infeasible} — it can "
                    "never be admitted; shrink the request or raise "
                    "the quota"
                )
        out: List[bool] = []
        for spec in specs:
            if spec.task_id in self._admitted:
                out.append(True)
                continue
            self._note_submit(spec.options)
            if (t is None or not t.quota
                    or spec.options.get("placement_group")):
                out.append(True)
            elif t.parked or not self._fits_quota(t, spec.resources):
                t.parked.append(spec)
                out.append(False)
            else:
                self._charge_admission(t, spec)
                out.append(True)
        return out

    def charge_reservation(
        self,
        key: bytes,
        tenant_name: str,
        resources: Dict[str, float],
    ) -> Optional[str]:
        """Quota-charge a placement-group reservation at creation (the
        resources are held exclusively whether or not tasks run in
        them). Returns an error string when the tenant's quota cannot
        accommodate it — reservations fail fast rather than queue.
        Released by release_admission(pg_id) on removal."""
        if not self.tenants:
            return None
        t = self.tenants.get(tenant_name or DEFAULT_TENANT)
        if t is None or not t.quota:
            return None
        if not self._fits_quota(t, resources):
            return (
                f"placement group needs {resources} but tenant "
                f"'{t.name}' has "
                f"{ {k: v for k, v in t.admitted.items() if v > 1e-9} } "
                f"admitted against quota {t.quota}"
            )
        self._admitted[key] = (t.name, dict(resources))
        for k, v in resources.items():
            t.admitted[k] = t.admitted.get(k, 0.0) + v
        return None

    @staticmethod
    def _fits_quota(t: TenantEntry, need: Dict[str, float]) -> bool:
        return all(
            t.admitted.get(k, 0.0) + need.get(k, 0.0) <= cap + 1e-9
            for k, cap in t.quota.items()
        )

    def _charge_admission(self, t: TenantEntry, spec) -> None:
        self._admitted[spec.task_id] = (t.name, dict(spec.resources))
        for k, v in spec.resources.items():
            t.admitted[k] = t.admitted.get(k, 0.0) + v

    def release_admission(self, task_id: bytes) -> None:
        """Final completion/failure (or permanent actor death, or PG
        removal): return the quota charge and wake the admission
        queue. Idempotent. Prunes the tenant once it is fully idle
        with no registered jobs left (a conn that dropped mid-flight
        must not orphan its TenantEntry — and its gauge — forever)."""
        charge = self._admitted.pop(task_id, None)
        if charge is None:
            return
        tenant_name, resources = charge
        t = self.tenants.get(tenant_name)
        if t is None:
            return
        for k, v in resources.items():
            t.admitted[k] = max(0.0, t.admitted.get(k, 0.0) - v)
        if (
            not t.parked
            and not any(v > 1e-9 for v in t.admitted.values())
            and not any(
                j.tenant == tenant_name for j in self.jobs.values()
            )
        ):
            del self.tenants[tenant_name]

    def pop_admissible(self) -> List[Any]:
        """Parked specs that now fit their tenant's quota, in FIFO
        order per tenant (head-of-queue only: quota order is part of
        the fairness contract)."""
        out: List[Any] = []
        for t in self.tenants.values():
            while t.parked and self._fits_quota(t, t.parked[0].resources):
                spec = t.parked.popleft()
                self._charge_admission(t, spec)
                out.append(spec)
        return out

    def pop_infeasible(self, tenant_name: str) -> List[Any]:
        """Parked specs that exceed the tenant's CURRENT quota outright
        (possible after a re-registration lowered it): remove and
        return them so the hub can fail them loudly — left in place
        they would wedge the FIFO queue forever."""
        t = self.tenants.get(tenant_name)
        if t is None or not t.quota:
            return []
        bad = [
            s for s in t.parked
            if any(
                s.resources.get(k, 0.0) > cap + 1e-9
                for k, cap in t.quota.items()
            )
        ]
        for s in bad:
            t.parked.remove(s)
        return bad

    def unpark(self, spec) -> bool:
        """Remove a parked spec (cancellation path)."""
        for t in self.tenants.values():
            try:
                t.parked.remove(spec)
                return True
            except ValueError:
                continue
        return False

    def parked_count(self) -> int:
        return sum(len(t.parked) for t in self.tenants.values())

    def parked_specs(self) -> List[Any]:
        return [s for t in self.tenants.values() for s in t.parked]

    # ------------------------------------------------------ usage accounting
    def charge_dispatch(self, spec) -> None:
        """A task left the queue for a worker: start its fair-share
        interval (actors keep it open for their whole lifetime)."""
        if not self.tenants or spec.task_id in self._running:
            return
        tenant_name = self.tenant_of(spec.options)
        job = self.jobs.get(spec.options.get("job_id") or "")
        if job is not None:
            job.dispatched += 1
        if tenant_name not in self.tenants:
            return  # unregistered tenant: no fair-share state to keep
        t = self.tenants[tenant_name]
        w = _work(spec.resources)
        self._fold(t)
        t.rate += w
        self._running[spec.task_id] = (tenant_name, w)

    def settle(self, task_id: bytes) -> None:
        """The task's resources were released (done, failed, retried,
        preempted, actor died): close its fair-share interval."""
        rec = self._running.pop(task_id, None)
        if rec is None:
            return
        tenant_name, w = rec
        t = self.tenants.get(tenant_name)
        if t is None:
            return
        self._fold(t)
        t.rate = max(0.0, t.rate - w)

    def _fold(self, t: TenantEntry) -> None:
        now = self.clock()
        t.usage_s = t.live_usage(now)
        t.rate_since = now

    # -------------------------------------------------------------- ordering
    def class_order_key(self, sched_class: tuple):
        """Sort key for runnable scheduling classes: higher priority
        first, then the tenant furthest below its weighted fair share.
        The class tuple ends with (..., tenant, priority) — see
        Hub._sched_class. Python's sort is stable, so equal keys keep
        queue insertion order (single-tenant behavior is unchanged)."""
        tenant, priority = sched_class[-2], sched_class[-1]
        t = self.tenants.get(tenant)
        deficit = 0.0
        if t is not None:
            deficit = t.live_usage(self.clock()) / t.weight()
        return (-priority, deficit)

    # ------------------------------------------------------------ preemption
    def preemption_victims(
        self,
        beneficiary_priority: int,
        need_chips: int,
        max_bundle: Dict[str, float],
        need_resources: Dict[str, float],
        ready_pgs: List[Any],
        running_tasks: List[Tuple[Any, Any]],
        free_chips_by_node: Dict[str, int],
        avail_by_node: Dict[str, Dict[str, float]],
    ) -> Tuple[List[Any], List[Tuple[Any, Any]]]:
        """Select victim gangs for a reservation that cannot fit.

        Candidates are ready placement groups and running plain tasks
        whose priority is STRICTLY below the beneficiary's; gangs are
        whole PGs (never individual bundles). Lowest priority bleeds
        first; within a priority, single tasks die before whole gangs
        (one retry loses less work than a gang restart), and among
        gangs the newest dies first (LIFO — the least sunk cost).
        Selection is greedy
        and NODE-AWARE: it stops once (a) cluster-wide freed
        chips+resources close the whole-gang gap AND (b) some single
        node can seat the LARGEST bundle whole — chips and its other
        resources co-located. Two 2-chip victims on different hosts
        cannot seat a 4-chip single-node bundle, and shedding them
        would be work lost for naught; if no victim set reaches
        feasibility, nothing is preempted. (Multi-bundle packing and
        ICI fragmentation within a node are still approximated; the
        reservation retry is the authority, and the hub's
        preempt-rounds cap bounds repeated misestimates.)
        Returns (victim_pgs, victim_tasks)."""
        cands: List[Tuple[tuple, str, Any]] = []
        for pg in ready_pgs:
            pri = int(getattr(pg, "priority", 0) or 0)
            if pri >= beneficiary_priority:
                continue
            # gangs sort AFTER single tasks within a priority (a whole
            # PG restart loses far more work than one task retry);
            # among gangs the newest dies first
            cands.append(((pri, 1, -getattr(pg, "seq", 0)), "pg", pg))
        for worker, spec in running_tasks:
            pri = self.priority_of(spec.options)
            if pri >= beneficiary_priority:
                continue
            cands.append(((pri, 0, 0), "task", (worker, spec)))
        cands.sort(key=lambda c: c[0])
        free_by_node = dict(free_chips_by_node)
        freed_res: Dict[str, Dict[str, float]] = {}
        avail_total: Dict[str, float] = {}
        for av in avail_by_node.values():
            for k, v in av.items():
                avail_total[k] = avail_total.get(k, 0.0) + v
        res_gap = {
            k: v - avail_total.get(k, 0.0)
            for k, v in need_resources.items()
            if k != "TPU" and v > avail_total.get(k, 0.0) + 1e-9
        }
        max_bundle_chips = int(max_bundle.get("TPU", 0))

        def feasible() -> bool:
            if res_gap:
                return False
            if need_chips > 0 and sum(free_by_node.values()) < need_chips:
                return False
            # co-location: one node must seat the largest bundle whole
            for nid in set(free_by_node) | set(avail_by_node):
                if free_by_node.get(nid, 0) < max_bundle_chips:
                    continue
                av = avail_by_node.get(nid, {})
                fr = freed_res.get(nid, {})
                if all(
                    av.get(k, 0.0) + fr.get(k, 0.0) >= v - 1e-9
                    for k, v in max_bundle.items()
                    if k != "TPU"
                ):
                    return True
            return False

        def take(nid: str, chips: int, resources: Dict[str, float]) -> None:
            free_by_node[nid] = free_by_node.get(nid, 0) + chips
            node_res = freed_res.setdefault(nid, {})
            for k, v in resources.items():
                if k == "TPU":
                    continue
                node_res[k] = node_res.get(k, 0.0) + v
                if k in res_gap:
                    res_gap[k] -= v
                    if res_gap[k] <= 1e-9:
                        del res_gap[k]

        def useful(chips: int, resources: Dict[str, float]) -> bool:
            # a victim must free something the reservation actually
            # lacks: chips, or a resource still in the cluster-wide
            # gap. (Freeing co-location-only resources on exactly the
            # chip node is NOT chased — conservatively preempting
            # nothing beats killing innocents on the wrong node.)
            if chips > 0:
                return True
            return any(k in res_gap for k in resources)

        victim_pgs: List[Any] = []
        victim_tasks: List[Tuple[Any, Any]] = []
        for _key, kind, victim in cands:
            if feasible():
                break
            if kind == "pg":
                pg = victim
                # chips freed per bundle: the reserved SLICE chunk when
                # there is one, else the bundle's TPU request —
                # PACK/SPREAD gangs hold chips through node avail and
                # worker pins, not bundle_chips, and must still be
                # creditable victims
                chunks = pg.bundle_chips or [()] * len(pg.bundles)
                freed = [
                    (b, nid, max(len(chunk), int(b.get("TPU", 0))))
                    for b, nid, chunk in zip(
                        pg.bundles, pg.bundle_nodes, chunks
                    )
                ]
                if not any(useful(c, b) for b, _, c in freed):
                    continue  # frees nothing the gap needs
                victim_pgs.append(pg)
                for b, nid, chips in freed:
                    take(nid, chips, b)
            else:
                worker, spec = victim
                freed_chips = len(worker.pinned_chips or ())
                if not useful(freed_chips, spec.resources):
                    continue
                victim_tasks.append(victim)
                take(worker.node_id, freed_chips, spec.resources)
        if not feasible():
            # even preempting every lower-priority gang cannot fit the
            # reservation: preempt nothing (don't shed work for naught)
            return [], []
        return victim_pgs, victim_tasks

    def note_preemption(self, options: dict) -> None:
        self.preemptions += 1
        t = self.tenants.get(self.tenant_of(options))
        if t is not None:
            t.n_preempted += 1
        job = self.jobs.get(options.get("job_id") or "")
        if job is not None:
            job.preempted += 1

    # ---------------------------------------------------------- introspection
    def job_table(self) -> List[dict]:
        return [
            {
                "job_id": e.job_id,
                "tenant": e.tenant,
                "priority": e.priority,
                "quota": dict(e.quota),
                "submitted": e.submitted,
                "dispatched": e.dispatched,
                "preempted": e.preempted,
            }
            for e in self.jobs.values()
        ]

    def tenant_table(self) -> List[dict]:
        now = self.clock()
        total_rate = sum(t.rate for t in self.tenants.values())
        return [
            {
                "tenant": t.name,
                "quota": dict(t.quota),
                "admitted": {
                    k: v for k, v in t.admitted.items() if v > 1e-9
                },
                "usage_s": round(t.live_usage(now), 6),
                "running_work": t.rate,
                "share": (t.rate / total_rate) if total_rate > 0 else 0.0,
                "pending_quota": len(t.parked),
                "preempted": t.n_preempted,
            }
            for t in self.tenants.values()
        ]
