"""Deterministic fault-injection plane.

One seeded engine replaces the scattered ``RAY_TPU_CHAOS_*`` env
parsers (the probabilistic hub drop hook and the object agent's bespoke
``close_after`` parser). The reference gets the same property from
``src/ray/rpc/rpc_chaos.h`` (env-selected per-method RPC failure); the
schedule-determinism discipline follows FoundationDB-style simulation
testing: a fault plan plus a seed IS the failure scenario, so a soak
run that finds a bug is reproducible by re-running the same plan.

Plan grammar (``RAY_TPU_CHAOS_PLAN``, ``;``-separated directives)::

    seed=<int>                              rng seed (default 0)
    drop:[scope.]<msg_type>@<p>             drop the message with prob p
    delay:[scope.]<msg_type>@<lo>-<hi>[@p]  delay handling by U(lo, hi)
    dup:[scope.]<msg_type>@<p>              deliver the message twice
    conn_kill:<role>[@<t>]                  kill one client|worker conn at t
    worker_kill:<n>[@<t>]                   SIGKILL n workers at t
    worker_hang:<n>[@<t>]                   SIGSTOP n workers at t (stall,
                                            not death — the watchdog or a
                                            per-task timeout_s must recover)
    partition:<node_id>@<t1>-<t2>           blackhole the node's inbound
                                            (heartbeats AND data) in [t1,t2)
    close_after:<n>                         object agents close every conn
                                            after serving n data chunks
                                            (mid-stream transfer death)
    replica_kill:<dep>[@<t>]                kill one serve replica of the
                                            deployment at t (victim drawn
                                            from the serve rng)
    slow_replica:<dep>@<lo>-<hi>[@p]        inject U(lo, hi) execute
                                            latency into the deployment's
                                            replicas (per request, prob p)
    route_partition:<dep>@<t1>-<t2>         blackhole router replica-list
                                            refresh for the deployment in
                                            [t1,t2) — handles run on their
                                            stale cached set

Durations accept ``10ms``, ``1.5s``, bare seconds, and the ``t+2s``
spelling (the ``t+`` prefix is cosmetic — all times are offsets from
engine arm). Example::

    seed=7;drop:submit_task@0.05;delay:get@10ms-50ms;conn_kill:client@t+2s;\
worker_hang:1;partition:node2@3s-5s

Scopes pick the process that injects the fault: ``hub`` (default — the
message is dropped/delayed/duplicated at the control plane's dispatch
seam, identically under both reactor topologies), ``client`` (a driver
or Ray-Client process intercepts its own outbound sends), ``worker``
(a worker's outbound sends, plus the pseudo message type ``exec`` which
stalls the task body before it runs), and ``agent`` (a node agent's
outbound sends — ``drop:agent.node_heartbeat@1`` is heartbeat
suppression without a full partition). Timed faults (conn_kill,
worker_kill, worker_hang, partition) execute only in the hub. The
``serve`` scope owns the serve-plane verbs: ``replica_kill`` executes
in the serve controller's reconcile loop, ``slow_replica`` in replica
processes, and ``route_partition`` in every routing handle.

Legacy aliases keep working: ``RAY_TPU_CHAOS_DROP="get:0.4,..."``
translates to hub ``drop:`` rules and
``RAY_TPU_CHAOS_OBJECT_AGENT="close_after:N"`` to ``close_after:N``.

Determinism contract: decisions come from one ``random.Random`` seeded
with ``(seed, scope)``, drawn once per rule-matched message in arrival
order. At hub scope arrivals are processed by a single thread, so an
identical message sequence yields an identical fault sequence; the
timed-fault schedule is a pure function of the plan. With no plan set
every injection point is gated on a cached ``None`` — zero per-message
work.
"""

from __future__ import annotations

import os
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

SCOPES = ("hub", "client", "worker", "agent", "object_agent", "serve")

# timed-fault kinds (hub-executed), in the grammar's spelling
TIMED_KINDS = ("conn_kill", "worker_kill", "worker_hang")
# timed-fault kinds the serve controller executes (serve scope)
SERVE_TIMED_KINDS = ("replica_kill",)


class PlanError(ValueError):
    """Malformed RAY_TPU_CHAOS_PLAN directive."""


@dataclass
class Rule:
    """One message-fault rule: drop/delay/dup on a msg_type at a scope."""

    kind: str            # "drop" | "delay" | "dup"
    scope: str           # "hub" | "client" | "worker" | "agent"
    msg_type: str
    prob: float = 1.0
    lo: float = 0.0      # delay window (seconds)
    hi: float = 0.0


@dataclass
class TimedFault:
    """One scheduled fault: fires once at ``at`` seconds after arm.
    ``arg`` is the victim selector (conn role, or worker count)."""

    kind: str            # "conn_kill" | "worker_kill" | "worker_hang"
    at: float
    arg: str = ""
    count: int = 1
    fired: int = 0       # victims already taken (worker_kill:3 fires 3x)


@dataclass
class Plan:
    seed: int = 0
    rules: List[Rule] = field(default_factory=list)
    timed: List[TimedFault] = field(default_factory=list)
    partitions: Dict[str, List[Tuple[float, float]]] = field(
        default_factory=dict
    )
    # deployment -> blackhole windows for router replica-list refresh
    route_partitions: Dict[str, List[Tuple[float, float]]] = field(
        default_factory=dict
    )
    close_after: int = 0
    text: str = ""


def _duration(tok: str) -> float:
    """'10ms' / '1.5s' / '2' / 't+2s' -> seconds."""
    tok = tok.strip()
    if tok.startswith("t+"):
        tok = tok[2:]
    try:
        if tok.endswith("ms"):
            return float(tok[:-2]) / 1000.0
        if tok.endswith("s"):
            return float(tok[:-1])
        return float(tok)
    except ValueError:
        raise PlanError(f"bad duration {tok!r}") from None


def _window(tok: str) -> Tuple[float, float]:
    """'10ms-50ms' / '3s-5s' -> (lo, hi) seconds."""
    lo, sep, hi = tok.partition("-")
    if not sep:
        raise PlanError(f"expected <lo>-<hi> window, got {tok!r}")
    a, b = _duration(lo), _duration(hi)
    if b < a:
        raise PlanError(f"window {tok!r} ends before it starts")
    return a, b


def _scoped(target: str) -> Tuple[str, str]:
    """'client.get' -> ('client', 'get'); bare 'get' -> ('hub', 'get')."""
    scope, dot, mt = target.partition(".")
    if dot and scope in SCOPES:
        return scope, mt
    return "hub", target


def parse_plan(text: str) -> Plan:
    plan = Plan(text=text.strip())
    for raw in text.split(";"):
        d = raw.strip()
        if not d:
            continue
        if d.startswith("seed="):
            try:
                plan.seed = int(d[5:])
            except ValueError:
                raise PlanError(f"bad seed {d!r}") from None
            continue
        verb, sep, rest = d.partition(":")
        verb = verb.strip()
        if not sep:
            raise PlanError(f"bad directive {d!r}")
        if verb in ("drop", "dup"):
            target, sep2, prob = rest.partition("@")
            scope, mt = _scoped(target.strip())
            if scope == "worker" and mt == "exec":
                # the exec pseudo-type is a stall hook, not a message:
                # there is nothing to drop or duplicate, and silently
                # accepting the rule would record phantom faults
                raise PlanError(
                    f"worker.exec supports only delay: (a stall): {d!r}"
                )
            try:
                p = float(prob) if sep2 else 1.0
            except ValueError:
                raise PlanError(f"bad probability in {d!r}") from None
            plan.rules.append(Rule(verb, scope, mt, prob=p))
        elif verb == "delay":
            parts = rest.split("@")
            if len(parts) < 2:
                raise PlanError(f"delay needs a window: {d!r}")
            scope, mt = _scoped(parts[0].strip())
            lo, hi = _window(parts[1])
            try:
                p = float(parts[2]) if len(parts) > 2 else 1.0
            except ValueError:
                raise PlanError(f"bad probability in {d!r}") from None
            plan.rules.append(Rule("delay", scope, mt, prob=p, lo=lo, hi=hi))
        elif verb == "conn_kill":
            role, _sep2, at = rest.partition("@")
            role = role.strip()
            if role not in ("client", "worker"):
                raise PlanError(f"conn_kill role must be client|worker: {d!r}")
            plan.timed.append(TimedFault(
                "conn_kill", _duration(at) if at else 1.0, arg=role,
            ))
        elif verb in ("worker_kill", "worker_hang"):
            n, _sep2, at = rest.partition("@")
            try:
                count = max(1, int(n))
            except ValueError:
                raise PlanError(f"bad count in {d!r}") from None
            plan.timed.append(TimedFault(
                verb, _duration(at) if at else 1.0, count=count,
            ))
        elif verb == "partition":
            node, sep2, win = rest.partition("@")
            if not sep2:
                raise PlanError(f"partition needs @<t1>-<t2>: {d!r}")
            plan.partitions.setdefault(node.strip(), []).append(_window(win))
        elif verb == "replica_kill":
            dep, _sep2, at = rest.partition("@")
            dep = dep.strip()
            if not dep:
                raise PlanError(f"replica_kill needs a deployment: {d!r}")
            plan.timed.append(TimedFault(
                "replica_kill", _duration(at) if at else 1.0, arg=dep,
            ))
        elif verb == "slow_replica":
            parts = rest.split("@")
            dep = parts[0].strip()
            if len(parts) < 2 or not dep:
                raise PlanError(
                    f"slow_replica needs <dep>@<lo>-<hi>: {d!r}"
                )
            lo, hi = _window(parts[1])
            try:
                p = float(parts[2]) if len(parts) > 2 else 1.0
            except ValueError:
                raise PlanError(f"bad probability in {d!r}") from None
            plan.rules.append(
                Rule("slow_replica", "serve", dep, prob=p, lo=lo, hi=hi)
            )
        elif verb == "route_partition":
            dep, sep2, win = rest.partition("@")
            dep = dep.strip()
            if not sep2 or not dep:
                raise PlanError(
                    f"route_partition needs <dep>@<t1>-<t2>: {d!r}"
                )
            plan.route_partitions.setdefault(dep, []).append(_window(win))
        elif verb == "close_after":
            try:
                plan.close_after = max(1, int(rest))
            except ValueError:
                raise PlanError(f"bad close_after in {d!r}") from None
        else:
            raise PlanError(f"unknown chaos verb {verb!r}")
    plan.timed.sort(key=lambda f: f.at)
    return plan


def plan_text_from_env(environ=None) -> str:
    """The effective plan: RAY_TPU_CHAOS_PLAN plus the legacy aliases
    (RAY_TPU_CHAOS_DROP / RAY_TPU_CHAOS_OBJECT_AGENT) appended as
    equivalent directives, so pre-plan deployments keep working."""
    # deliberately env-only (NOT the config table): engines are built
    # in worker/agent/client processes that never run config.reload(),
    # and a plan baked into a stale config snapshot would resurrect
    # faults after the env was cleared. The env var IS the contract.
    env = os.environ if environ is None else environ
    parts = []
    plan = (env.get("RAY_TPU_CHAOS_PLAN") or "").strip()
    if plan:
        parts.append(plan)
    legacy_drop = (env.get("RAY_TPU_CHAOS_DROP") or "").strip()
    for part in legacy_drop.split(","):
        if ":" in part:
            mt, prob = part.rsplit(":", 1)
            try:
                float(prob)
            except ValueError:
                continue
            parts.append(f"drop:{mt.strip()}@{prob}")
    legacy_agent = (env.get("RAY_TPU_CHAOS_OBJECT_AGENT") or "").strip()
    if legacy_agent.startswith("close_after:"):
        try:
            n = int(legacy_agent.split(":", 1)[1])
        except ValueError:
            n = 0
        if n > 0:
            parts.append(f"close_after:{n}")
    return ";".join(parts)


class ChaosEngine:
    """The per-process injection engine: scope-filtered rules from one
    shared plan, a seeded rng, per-fault trigger counters, and a
    bounded recent-event log (surfaced via ``list_state("chaos")`` and
    the ``ray_tpu chaos`` CLI)."""

    def __init__(self, plan_text: str, scope: str = "hub"):
        self.plan = parse_plan(plan_text)
        self.scope = scope
        # scope-filtered rule index: msg_type -> rules, checked per
        # message. Scopes other than this process's contribute nothing.
        # slow_replica rules live in their own index (keyed by
        # deployment, consulted by execute_delay — not a message fault).
        self.rules: Dict[str, List[Rule]] = {}
        self.slow_rules: Dict[str, List[Rule]] = {}
        for r in self.plan.rules:
            if r.scope != scope:
                continue
            if r.kind == "slow_replica":
                self.slow_rules.setdefault(r.msg_type, []).append(r)
            else:
                self.rules.setdefault(r.msg_type, []).append(r)
        if scope == "hub":
            self.timed = [
                f for f in self.plan.timed if f.kind in TIMED_KINDS
            ]
        elif scope == "serve":
            self.timed = [
                f for f in self.plan.timed if f.kind in SERVE_TIMED_KINDS
            ]
        else:
            self.timed = []
        self.partitions = self.plan.partitions if scope == "hub" else {}
        self.route_partitions = (
            self.plan.route_partitions if scope == "serve" else {}
        )
        self.close_after = (
            self.plan.close_after if scope == "object_agent" else 0
        )
        # (seed, scope) keeps sibling processes' draw sequences
        # independent — a worker consuming draws must not shift the
        # hub's schedule
        self.rng = random.Random(f"{self.plan.seed}:{scope}")
        self.counts: Dict[str, int] = {}
        self.events: deque = deque(maxlen=256)
        self._t0: Optional[float] = None

    @property
    def active(self) -> bool:
        """Does this scope have anything to inject? Inactive engines
        are replaced by None so the hot path pays one attribute load."""
        return bool(
            self.rules or self.slow_rules or self.timed
            or self.partitions or self.route_partitions or self.close_after
        )

    # ------------------------------------------------------------ lifecycle
    def arm(self, now: Optional[float] = None) -> None:
        """Start the timed-fault/partition clock (monotonic)."""
        self._t0 = time.monotonic() if now is None else now

    def elapsed(self, now: Optional[float] = None) -> float:
        if self._t0 is None:
            return 0.0
        return (time.monotonic() if now is None else now) - self._t0

    # ------------------------------------------------------------- messages
    def message_action(self, msg_type: str):
        """One decision per matched message: None (pass), ("drop",),
        ("delay", seconds), or ("dup",). Draw order is arrival order,
        so a fixed message sequence yields a fixed fault sequence."""
        rules = self.rules.get(msg_type)
        if not rules:
            return None
        for r in rules:
            if r.prob < 1.0 and self.rng.random() >= r.prob:
                continue
            if r.kind == "drop":
                self.record("drop", msg_type=msg_type)
                return ("drop",)
            if r.kind == "dup":
                self.record("dup", msg_type=msg_type)
                return ("dup",)
            d = r.lo if r.hi <= r.lo else self.rng.uniform(r.lo, r.hi)
            self.record("delay", msg_type=msg_type, delay_s=round(d, 6))
            return ("delay", d)
        return None

    def outbound_send(self, msg_type: str) -> int:
        """message_action applied to an outbound send — the ONE
        decision-to-action mapping every sender scope (client, worker,
        agent) shares: 0 = drop the send, 1 = send, 2 = send twice. A
        delay stalls the calling thread inline (issuance latency, the
        sender-side analogue of a slow link)."""
        act = self.message_action(msg_type)
        if act is None:
            return 1
        kind = act[0]
        if kind == "drop":
            return 0
        if kind == "delay":
            time.sleep(act[1])
            return 1
        return 2

    # --------------------------------------------------------- timed faults
    def due_faults(self, now: Optional[float] = None) -> List[TimedFault]:
        """Timed faults whose deadline passed (left in the schedule;
        the executor pops victims via ``consume``/``defer``)."""
        t = self.elapsed(now)
        return [f for f in self.timed if f.at <= t and f.fired < f.count]

    def consume(self, fault: TimedFault, n: int = 1) -> None:
        fault.fired += n
        if fault.fired >= fault.count:
            try:
                self.timed.remove(fault)
            except ValueError:
                pass

    def defer(self, fault: TimedFault, by: float = 0.25) -> None:
        """No eligible victim yet (e.g. worker_kill before any worker
        spawned): retry the fault a beat later."""
        fault.at = self.elapsed() + by

    def partition_active(self, node_id: str,
                         now: Optional[float] = None) -> bool:
        wins = self.partitions.get(node_id)
        if not wins:
            return False
        t = self.elapsed(now)
        return any(lo <= t < hi for lo, hi in wins)

    # ---------------------------------------------------------- serve scope
    def execute_delay(self, deployment: str) -> float:
        """slow_replica draw for one request on this deployment's
        replica: injected execute latency in seconds (0.0 = none).
        Draws ride the scope rng in arrival order, so a fixed request
        sequence yields a fixed delay sequence."""
        rules = self.slow_rules.get(deployment)
        if not rules:
            return 0.0
        for r in rules:
            if r.prob < 1.0 and self.rng.random() >= r.prob:
                continue
            d = r.lo if r.hi <= r.lo else self.rng.uniform(r.lo, r.hi)
            self.record("slow_replica", deployment=deployment,
                        delay_s=round(d, 6))
            return d
        return 0.0

    def route_partition_active(self, deployment: str,
                               now: Optional[float] = None) -> bool:
        """Is the router-refresh blackhole window open for this
        deployment? Handles keep serving their stale cached replica
        set for the duration."""
        wins = self.route_partitions.get(deployment)
        if not wins:
            return False
        t = self.elapsed(now)
        return any(lo <= t < hi for lo, hi in wins)

    # ------------------------------------------------------------ reporting
    def record(self, kind: str, **fields) -> None:
        self.counts[kind] = self.counts.get(kind, 0) + 1
        ev = {"t": round(self.elapsed(), 4), "kind": kind}
        ev.update(fields)
        self.events.append(ev)

    def snapshot(self) -> dict:
        return {
            "plan": self.plan.text,
            "seed": self.plan.seed,
            "scope": self.scope,
            "armed": self._t0 is not None,
            "elapsed_s": round(self.elapsed(), 3) if self._t0 else 0.0,
            "counts": dict(self.counts),
            "pending_timed": [
                {"kind": f.kind, "at_s": f.at, "arg": f.arg,
                 "count": f.count, "fired": f.fired}
                for f in self.timed
            ],
            "partitions": {
                n: [list(w) for w in wins]
                for n, wins in self.partitions.items()
            },
            "route_partitions": {
                n: [list(w) for w in wins]
                for n, wins in self.route_partitions.items()
            },
            "close_after": self.close_after,
            "events": list(self.events),
        }


def engine_for(scope: str, environ=None) -> Optional[ChaosEngine]:
    """The ONE constructor every injection point uses: returns an armed
    engine when the plan has faults for this scope, else None — the
    cached-None check is the entire cost of an inert fault plane."""
    text = plan_text_from_env(environ)
    if not text:
        return None
    eng = ChaosEngine(text, scope=scope)
    if not eng.active:
        return None
    eng.arm()
    return eng
