"""Framework-wide config table with env overrides.

Parity: src/ray/common/ray_config_def.h (224 RAY_CONFIG entries read
from RAY_xxx env vars) — a single typed table every subsystem reads
instead of scattering magic numbers. Override any knob with
RAY_TPU_<NAME>=<value>; values are parsed to the default's type.
"""

from __future__ import annotations

import os
from typing import Any, Dict

_DEFAULTS: Dict[str, Any] = {
    # object plane
    "inline_object_threshold": 100 * 1024,   # plasma-vs-inline cutoff
    "object_store_memory": 0.0,              # 0 = unlimited (no spill)
    # out-of-band object plane (object_agent.py): per-node data-plane
    # endpoints + the hub's ownership/location directory. object_agent
    # turns the serving side on; object_direct turns the consuming side
    # (resolve-then-pull / direct put) on — with either off, transfers
    # ride the hub-relay path exactly as before.
    "object_agent": True,
    "object_direct": True,
    # readiness push: wait() over not-ready refs subscribes once and the
    # hub pushes ready sets; off = the classic parked-WAIT request path
    "ready_push": True,
    # serve data plane: request/response payloads strictly larger than
    # this spill onto the direct object plane (serve/_private/
    # payloads.py) instead of riding VAL_INLINE through the hub;
    # 0 disables spilling. Deliberately below inline_object_threshold:
    # a serve payload crosses the wire twice (handle->replica,
    # replica->consumer), so the object plane pays off earlier.
    "serve_inline_max": 64 * 1024,
    # HTTP ingress request-body cap (aiohttp client_max_size). The
    # payload plane makes multi-MiB bodies routine; aiohttp's 1 MiB
    # default would 413 them at the front door.
    "serve_http_max_body": 1 << 30,
    # serve resilience: end-to-end request deadline (seconds). Born at
    # the router, rides request_meta to the replica and batch queue,
    # and bounds every blocking wait on the way (the proxy's result()
    # and the router's no-replica wait derive from it — no more literal
    # 60 s / 30 s). 0 = no deadline. Per-request override:
    # handle.options(request_timeout_s=...).
    "serve_request_timeout_s": 60.0,
    # admission control: cap on a handle's outstanding (routed, not yet
    # settled) requests per deployment; past it, new requests shed
    # immediately with a retriable error (HTTP 503) instead of queueing
    # into a timeout. 0 = unlimited. Per-deployment override:
    # @serve.deployment(max_queued_requests=N).
    "serve_max_queued_requests": 0,
    # router-side replica health ejection: a replica failing this many
    # consecutive requests is removed from the candidate set and
    # re-probed with jittered exponential backoff until healthy again
    "serve_ejection_failures": 3,
    "serve_probe_base_s": 0.25,     # ejected-replica re-probe backoff base
    "serve_probe_max_s": 5.0,       # ...and ceiling
    # transparent replica-retry budget (replica died mid-request):
    # bounded attempts with growing jittered delay, deadline-capped
    "serve_retry_attempts": 3,
    "serve_retry_base_s": 0.05,
    # driver-side warm segment pool: pre-create + pre-fault this many
    # bytes of pooled tmpfs segments in the background at init, so the
    # FIRST large put already memcpys into faulted pages (the plasma
    # arena trick). Split into two segments (each serves one put up to
    # half the budget; the default's 264 MiB halves cover 256 MiB-class
    # objects with slack, so carving one truncates away only a few MiB
    # of warm tail pages). 0 = off.
    "segment_prewarm_bytes": 2 * 264 * 1024 * 1024,
    # control plane: reactor shard count for the hub. 0 = auto
    # (min(4, cpu count)); 1 = the original single-reactor loop
    # (byte-for-byte identical wire behavior); N>1 = N reactor shard
    # threads + a state-plane thread (hub_shards.py)
    "hub_shards": 0,
    # scheduling / workers
    "worker_reap_period_s": 1.0,
    "max_pending_spawns_per_node": 32,
    # rpc: retry-safe requests retransmit with capped exponential
    # backoff + jitter — period is the base delay (0 = retransmit OFF:
    # requests park on their first send), max is the backoff ceiling
    "request_retry_period_s": 2.0,
    "request_retry_max_s": 30.0,
    "client_batch_max": 128,
    # transparent auto-batching: plain .remote() calls to the same
    # template that land within this window (microseconds) ship as ONE
    # SUBMIT_TASKS frame through the bulk ABI (client.py
    # submit_batched). ObjectRefs still return synchronously; the
    # window only delays the WIRE flush. 0 disables — every call rides
    # the classic per-call SUBMIT_TASK frame; batch_window()/map()
    # still batch explicitly either way.
    "submit_autobatch_window_us": 300,
    # memory monitor (reference: common/memory_monitor.h + raylet
    # worker_killing_policy.cc) — kill the newest worker past the cap
    "memory_monitor_period_s": 1.0,
    "memory_usage_threshold": 0.0,           # bytes/worker; 0 = disabled
    # observability
    "task_events_max": 20000,
    "runtime_events_max": 2000,          # flight-recorder ring size
    "builtin_metrics": True,             # ray_tpu_* runtime self-metrics
    # sampling profiler (profiling.py): wall-clock sample rate in Hz for
    # the per-process daemon sampler. 0 (the default) = the sampler
    # thread is never created and no PROFILE_BATCH frames exist on the
    # wire — the only residue is one env read at process start. Workers
    # and clients read the RAY_TPU_PROFILE_HZ env directly (like
    # chaos_plan: they never run reload()).
    "profile_hz": 0.0,
    "profile_overhead_budget": 0.03,     # self-overhead ratio past which
                                         # the sampler halves its rate
                                         # (auto-clamp; 0 = never clamp)
    "profile_flush_period_s": 1.0,       # local fold -> hub batch cadence
    "profile_store_max": 4096,           # hub cap on distinct folded
                                         # stacks kept per process
    "node_heartbeat_period_s": 2.0,      # per-node gauge cadence; 0 = off
    "flight_recorder_path": "",          # "" = <session_dir>/flight_recorder.json
    # fault tolerance (reference: num_heartbeats_timeout in
    # ray_config_def.h — the GCS declares a raylet dead after N missed
    # heartbeats; here the threshold counts node_heartbeat_period_s
    # periods, so 15 * 2s = 30s matches the reference default)
    "node_heartbeat_miss_threshold": 15,  # missed periods -> node death; 0 = off
    # hung-worker watchdog: every dispatched task gets this execute
    # deadline unless it carries its own options(timeout_s=...); past
    # it the worker is SIGKILLed and the task retries per its budget
    # (a SIGSTOP'd/hung worker never EOFs on its own). 0 = off.
    "task_timeout_default_s": 0.0,
    # fault injection: documents RAY_TPU_CHAOS_PLAN (chaos.py grammar;
    # RAY_TPU_CHAOS_DROP / RAY_TPU_CHAOS_OBJECT_AGENT stay as legacy
    # aliases). chaos.py reads the ENV directly, not this snapshot:
    # engines are built in worker/agent/client processes that never
    # run reload(), and a plan baked into a stale snapshot would
    # resurrect faults after the env was cleared.
    "chaos_plan": "",
}


class _Config:
    def __init__(self):
        self._values: Dict[str, Any] = {}
        for key, default in _DEFAULTS.items():
            env = os.environ.get(f"RAY_TPU_{key.upper()}")
            if env is None:
                self._values[key] = default
            else:
                self._values[key] = self._parse(env, default)

    @staticmethod
    def _parse(raw: str, default: Any) -> Any:
        if isinstance(default, bool):
            return raw.lower() in ("1", "true", "yes")
        if isinstance(default, int):
            return int(float(raw))
        if isinstance(default, float):
            return float(raw)
        return raw

    def __getattr__(self, key: str) -> Any:
        try:
            return self._values[key]
        except KeyError:
            raise AttributeError(key) from None

    def get(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def set(self, key: str, value: Any) -> None:
        """Test/driver override (before the consuming subsystem starts)."""
        self._values[key] = value

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)


RAY_TPU_CONFIG = _Config()


def reload() -> None:
    """Re-read env overrides (a new Hub calls this so per-test env
    changes take effect without a fresh interpreter)."""
    global RAY_TPU_CONFIG
    RAY_TPU_CONFIG = _Config()
