"""Durable KV store backend for the hub.

Parity: the reference GCS chooses a storage backend at startup —
in-memory or Redis for fault tolerance (gcs/gcs_server/gcs_server.h
StorageType, gcs/store_client/redis_store_client.h); the internal KV
(function table, Serve/Tune metadata, usage tags) survives a GCS
restart. Here the durable backend is a local append-only log +
snapshot (no Redis in a TPU pod's trust domain; the head's disk is
the natural store). Enable with ``ray_tpu.init(_kv_store_path=...)``
or RAY_TPU_KV_STORE_PATH; a restarted head reloads the table and
compacts the log.

Format: snapshot file = pickled dict; log file = pickled ("put", k, v)
/ ("del", k) records appended per mutation. Torn tails (crash mid-
append) are detected and dropped on load.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Dict, Optional

_LEN = struct.Struct("<I")


class FileKvStore:
    def __init__(self, path: str, fsync: bool = False):
        self.dir = path
        os.makedirs(path, exist_ok=True)
        self._snap_path = os.path.join(path, "kv.snapshot")
        self._log_path = os.path.join(path, "kv.log")
        self._fsync = fsync
        self._log = None  # opened by load()
        # exclusive owner lock: a second hub opening the same store would
        # truncate the log out from under the first (load() -> compact
        # reopens 'wb'), interleaving appends and corrupting replay
        import fcntl

        self._lock_f = open(os.path.join(path, "kv.lock"), "w")
        try:
            fcntl.flock(self._lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_f.close()
            raise RuntimeError(
                f"KV store {path!r} is already owned by another live hub"
            )

    # -- recovery ------------------------------------------------------
    def load(self) -> Dict[bytes, bytes]:
        """Snapshot + replayed log -> table; then compact (rewrite the
        snapshot, truncate the log) so recovery cost stays bounded."""
        kv: Dict[bytes, bytes] = {}
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    kv = pickle.load(f)
            except Exception:
                kv = {}
        if os.path.exists(self._log_path):
            with open(self._log_path, "rb") as f:
                while True:
                    hdr = f.read(_LEN.size)
                    if len(hdr) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(hdr)
                    blob = f.read(n)
                    if len(blob) < n:
                        break  # torn tail from a crash mid-append
                    try:
                        rec = pickle.loads(blob)
                    except Exception:
                        break
                    if rec[0] == "put":
                        kv[rec[1]] = rec[2]
                    elif rec[0] == "del":
                        kv.pop(rec[1], None)
        self._compact(kv)
        return kv

    def _compact(self, kv: Dict[bytes, bytes]) -> None:
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(kv, f, protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        self._log = open(self._log_path, "wb")

    # -- mutation log --------------------------------------------------
    def _append(self, rec) -> None:
        if self._log is None:
            self._log = open(self._log_path, "ab")
        blob = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        self._log.write(_LEN.pack(len(blob)) + blob)
        self._log.flush()
        if self._fsync:
            os.fsync(self._log.fileno())

    def record_put(self, key: bytes, value: bytes) -> None:
        self._append(("put", key, value))

    def record_del(self, key: bytes) -> None:
        self._append(("del", key))

    def close(self) -> None:
        if self._log is not None:
            try:
                self._log.close()
            except OSError:
                pass
            self._log = None
        if self._lock_f is not None:
            try:
                self._lock_f.close()  # releases the flock
            except OSError:
                pass
            self._lock_f = None


def open_store(path: Optional[str], fsync: bool = False) -> Optional[FileKvStore]:
    if not path:
        return None
    return FileKvStore(path, fsync=fsync)
