"""Guarded JAX accelerator discovery.

jax.devices() initializes the PJRT plugin; on a tunneled TPU (axon)
that can block for minutes when the tunnel is wedged. Nothing in the
control plane is allowed to hang on accelerator discovery, so the
probe runs in a throwaway subprocess with a hard timeout unless a
backend is already live in-process (then it's cheap and exact). The
default timeout (RAY_TPU_DETECT_TIMEOUT, 20s) keeps init() snappy on a
wedged tunnel; accelerator-seeking callers (bench.py) pass a longer
one that covers a healthy first TPU init (~20-40s).

This is the single probe implementation — bench.py and init() both
use it; keep it that way so the timeout semantics can't diverge.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

_cached: Optional[Tuple[str, int]] = None  # (platform, tpu_count)
# timeout the cached result was obtained with: a FAILED probe is only
# authoritative for timeouts <= this; a later caller with a longer
# timeout (bench) re-probes instead of inheriting the stale miss
_cached_timeout: float = 0.0


def probe_accelerator(
    timeout_s: Optional[float] = None, force: bool = False
) -> Tuple[str, int]:
    """(platform of device 0, TPU/axon device count), without ever
    blocking past the timeout. ("", 0) on any failure.

    Without ``force``, returns ("", 0) instantly when jax was never
    imported in this process — a CPU-only init() must not pay a
    subprocess jax import. Callers that exist to find an accelerator
    (bench.py) pass force=True and a generous timeout that covers first
    TPU init (~20-40s).
    """
    global _cached, _cached_timeout
    if timeout_s is None:
        timeout_s = float(os.environ.get("RAY_TPU_DETECT_TIMEOUT", "20"))
    if _cached is not None:
        if _cached != ("", 0) or timeout_s <= _cached_timeout:
            return _cached
        # cached miss, but this caller allows a longer probe: retry
    if not force and "jax" not in sys.modules:
        return ("", 0)  # not cached: a later forced probe may differ
    if "jax" in sys.modules:
        import jax

        backends_live = False
        try:
            backends_live = bool(jax._src.xla_bridge._backends)
        except AttributeError:
            pass  # private attr moved; fall through to the subprocess
        if backends_live:
            try:
                devs = jax.devices()
                _cached = (
                    devs[0].platform if devs else "",
                    sum(1 for d in devs if d.platform in ("tpu", "axon")),
                )
            except Exception:
                _cached = ("", 0)
            _cached_timeout = float("inf")  # in-process answer is exact
            return _cached
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; ds = jax.devices(); "
                "print(ds[0].platform if ds else '', "
                "sum(1 for d in ds if d.platform in ('tpu', 'axon')))",
            ],
            capture_output=True,
            timeout=timeout_s,
        )
        platform, count = out.stdout.decode().split()
        _cached = (platform, int(count))
    except Exception:
        _cached = ("", 0)
    _cached_timeout = timeout_s
    return _cached


def safe_tpu_device_count() -> int:
    """TPU/axon device count; 0 on any failure. Never hangs, and free
    when jax was never imported in this process."""
    return probe_accelerator()[1]


def tpu_env_markers() -> bool:
    """True when the environment advertises a TPU (GCE metadata env,
    axon tunnel, explicit accelerator type) — probing is then worth a
    subprocess jax import even if this process never imported jax."""
    return any(
        os.environ.get(k)
        for k in (
            "TPU_ACCELERATOR_TYPE",
            "TPU_NAME",
            "PALLAS_AXON_POOL_IPS",
            "PALLAS_AXON_TPU_GEN",
        )
    )


def reset_probe_cache() -> None:
    """Drop the cached probe result (tests; tunnel recovery)."""
    global _cached, _cached_timeout
    _cached = None
    _cached_timeout = 0.0
