"""Guarded JAX accelerator discovery.

jax.devices() initializes the PJRT plugin; on a tunneled TPU (axon)
that can block for minutes when the tunnel is wedged. Nothing in the
control plane is allowed to hang on accelerator discovery, so the
probe runs in a throwaway subprocess with a hard timeout unless a
backend is already live in-process (then it's cheap and exact).
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_cached: Optional[int] = None


def safe_tpu_device_count() -> int:
    """TPU/axon device count, never blocking longer than
    RAY_TPU_DETECT_TIMEOUT (default 20s). Returns 0 on any failure."""
    global _cached
    if _cached is not None:
        return _cached
    if "jax" not in sys.modules:
        _cached = 0
        return 0
    import jax

    if jax._src.xla_bridge._backends:
        try:
            _cached = sum(
                1 for d in jax.devices() if d.platform in ("tpu", "axon")
            )
        except Exception:
            _cached = 0
        return _cached
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print(sum(1 for d in jax.devices()"
                " if d.platform in ('tpu', 'axon')))",
            ],
            capture_output=True,
            timeout=float(os.environ.get("RAY_TPU_DETECT_TIMEOUT", "20")),
        )
        _cached = int(out.stdout.strip() or 0)
    except Exception:
        _cached = 0
    return _cached
