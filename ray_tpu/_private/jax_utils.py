"""Guarded JAX accelerator discovery.

jax.devices() initializes the PJRT plugin; on a tunneled TPU (axon)
that can block for minutes when the tunnel is wedged. Nothing in the
control plane is allowed to hang on accelerator discovery, so the
probe runs in a throwaway subprocess with a hard timeout unless a
backend is already live in-process (then it's cheap and exact). The
timeout (RAY_TPU_DETECT_TIMEOUT, default 120s) must comfortably cover
a healthy first TPU init (~20-40s).

This is the single probe implementation — bench.py and init() both
use it; keep it that way so the timeout semantics can't diverge.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

_cached: Optional[Tuple[str, int]] = None  # (platform, tpu_count)


def _timeout_s() -> float:
    return float(os.environ.get("RAY_TPU_DETECT_TIMEOUT", "120"))


def probe_accelerator() -> Tuple[str, int]:
    """(platform of device 0, TPU/axon device count), without ever
    blocking past the detect timeout. ("", 0) on any failure."""
    global _cached
    if _cached is not None:
        return _cached
    if "jax" in sys.modules:
        import jax

        backends_live = False
        try:
            backends_live = bool(jax._src.xla_bridge._backends)
        except AttributeError:
            pass  # private attr moved; fall through to the subprocess
        if backends_live:
            try:
                devs = jax.devices()
                _cached = (
                    devs[0].platform if devs else "",
                    sum(1 for d in devs if d.platform in ("tpu", "axon")),
                )
            except Exception:
                _cached = ("", 0)
            return _cached
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; ds = jax.devices(); "
                "print(ds[0].platform if ds else '', "
                "sum(1 for d in ds if d.platform in ('tpu', 'axon')))",
            ],
            capture_output=True,
            timeout=_timeout_s(),
        )
        platform, count = out.stdout.decode().split()
        _cached = (platform, int(count))
    except Exception:
        _cached = ("", 0)
    return _cached


def safe_tpu_device_count() -> int:
    """TPU/axon device count; 0 on any failure. Never hangs."""
    return probe_accelerator()[1]


def reset_probe_cache() -> None:
    """Drop the cached probe result (tests; tunnel recovery)."""
    global _cached
    _cached = None
