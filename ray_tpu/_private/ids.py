"""Unique identifiers for tasks, objects, and actors.

Design follows the reference's nested-ID scheme (reference:
src/ray/common/id.h and src/ray/design_docs/id_specification.md — JobID
4B ⊂ ActorID 16B ⊂ TaskID 24B ⊂ ObjectID 28B) but simplified: IDs here
are flat random byte strings. The nesting in the reference exists to
support distributed lineage reconstruction by-prefix; our control
service is authoritative for metadata, so flat IDs suffice and are
cheaper to generate and hash.
"""

from __future__ import annotations

import os
import binascii
import threading

_ID_LEN = 14  # bytes; 112 bits of randomness — collision-free in practice

# Batched entropy: os.urandom is a syscall, and ID generation sits on
# the submit hot path (TaskID + per-return ObjectID per call) — at 1k
# submits/s the per-call syscalls measurably steal GIL time from the
# in-process hub thread (BENCH_NOTE.md). One urandom refill serves 1024
# IDs; the bytes come from the same CSPRNG, so collision behavior is
# unchanged. Per-thread buffers keep this lock-free.
_ID_POOL_IDS = 1024
_entropy = threading.local()
if hasattr(os, "register_at_fork"):
    # a forked child must not replay the parent's pooled bytes (workers
    # here are spawned, not forked — this is defense in depth)
    os.register_at_fork(
        after_in_child=lambda: setattr(_entropy, "buf", None)
    )


def _pooled_id_bytes() -> bytes:
    buf = getattr(_entropy, "buf", None)
    pos = getattr(_entropy, "pos", 0)
    if buf is None or pos >= len(buf):
        buf = _entropy.buf = os.urandom(_ID_LEN * _ID_POOL_IDS)
        pos = 0
    _entropy.pos = pos + _ID_LEN
    return buf[pos:pos + _ID_LEN]


def id_slab(n: int) -> list:
    """``n`` raw id byte strings in one draw. A bulk submit needs
    N task ids + N*num_returns object ids up front; drawing them one
    at a time costs a pool-bookkeeping round per id and, every 1024
    ids, a syscall mid-loop. One sized urandom (plus whatever is left
    in the thread pool) amortizes both across the slab."""
    buf = getattr(_entropy, "buf", None)
    pos = getattr(_entropy, "pos", 0)
    if buf is None:
        buf, pos = b"", 0
    avail = (len(buf) - pos) // _ID_LEN
    out = [buf[pos + i * _ID_LEN: pos + (i + 1) * _ID_LEN]
           for i in range(min(n, avail))]
    _entropy.pos = pos + len(out) * _ID_LEN
    if len(out) < n:
        need = n - len(out)
        # refill covers the remainder AND leaves a full pool behind
        fresh = os.urandom(_ID_LEN * (need + _ID_POOL_IDS))
        out.extend(fresh[i * _ID_LEN: (i + 1) * _ID_LEN]
                   for i in range(need))
        _entropy.buf = fresh
        _entropy.pos = need * _ID_LEN
    return out


def id_pair() -> tuple:
    """Two pooled ids in one draw — the per-call ``.remote()`` shape
    (one task id + one return object id). Same entropy pool as
    ``id_slab``, minus the per-call slab bookkeeping: this sits on the
    client's batched-submit hot path (bench_core submit_path_overhead)."""
    buf = getattr(_entropy, "buf", None)
    pos = getattr(_entropy, "pos", 0)
    end = pos + 2 * _ID_LEN
    if buf is None or end > len(buf):
        buf = _entropy.buf = os.urandom(_ID_LEN * _ID_POOL_IDS)
        pos, end = 0, 2 * _ID_LEN
    _entropy.pos = end
    mid = pos + _ID_LEN
    return buf[pos:mid], buf[mid:end]


def span_id_hex() -> str:
    """16-hex-char tracing span/trace id from the same pooled entropy
    (util/tracing.py): span open is a hot path when runtime sampling is
    on, and a uuid.uuid4() per span costs an os.urandom syscall each."""
    return _pooled_id_bytes()[:8].hex()


class BaseID:
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def generate(cls):
        return cls(_pooled_id_bytes())

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(binascii.unhexlify(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class JobID(BaseID):
    pass
