"""AcceleratorManager ABC (reference:
python/ray/_private/accelerators/accelerator.py)."""

from __future__ import annotations

from typing import Dict, List, Optional


class AcceleratorManager:
    """Static-method interface, one subclass per accelerator family."""

    @staticmethod
    def get_resource_name() -> str:
        raise NotImplementedError

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        raise NotImplementedError

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        raise NotImplementedError

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        raise NotImplementedError

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        return {}

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        return (True, None)

    @staticmethod
    def set_current_process_visible_accelerators(ids: List[str]) -> None:
        raise NotImplementedError
