"""TPUAcceleratorManager (reference:
python/ray/_private/accelerators/tpu.py:109).

Detection is env-first (TPU VM standard vars + this runtime's knobs +
live jax when already imported); the reference's GCE-metadata fallback
needs egress air-gapped pods don't have. Emits the same resource shape:
``TPU`` chips, ``TPU-<accelerator_type>`` (:352) and the per-pod name
resource ``TPU-<pod>-head`` style gang-affinity key (:375).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from .accelerator import AcceleratorManager


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return "TPU_VISIBLE_CHIPS"

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        from ray_tpu.util.accelerators import tpu as helpers

        return helpers.get_num_tpu_chips_on_node()

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        acc = os.environ.get("TPU_ACCELERATOR_TYPE")
        if acc:
            return f"TPU-{acc}"
        gen = os.environ.get("PALLAS_AXON_TPU_GEN")
        if gen:
            return f"TPU-{gen.split(':')[0]}"
        return None

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        from ray_tpu.util.accelerators import tpu as helpers

        pod = helpers.get_current_pod_name()
        if pod:
            # pod-name resource: schedule a gang onto one specific pod
            # (reference tpu.py:375 TPU-{name} affinity resource)
            return {f"TPU-{pod}": 1.0}
        return {}

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        if quantity != int(quantity):
            return (False, "TPU chip requests must be whole chips")
        return (True, None)

    @staticmethod
    def set_current_process_visible_accelerators(ids: List[str]) -> None:
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in ids)
