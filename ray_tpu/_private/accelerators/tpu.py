"""TPUAcceleratorManager (reference:
python/ray/_private/accelerators/tpu.py:109).

Detection is env-first (TPU VM standard vars + this runtime's knobs +
live jax when already imported); the reference's GCE-metadata fallback
needs egress air-gapped pods don't have. Emits the same resource shape:
``TPU`` chips, ``TPU-<accelerator_type>`` (:352) and the per-pod name
resource ``TPU-<pod>-head`` style gang-affinity key (:375).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from .accelerator import AcceleratorManager


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return "TPU_VISIBLE_CHIPS"

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        from ray_tpu.util.accelerators import tpu as helpers

        return helpers.get_num_tpu_chips_on_node()

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        acc = os.environ.get("TPU_ACCELERATOR_TYPE")
        if acc:
            return f"TPU-{acc}"
        gen = os.environ.get("PALLAS_AXON_TPU_GEN")
        if gen:
            return f"TPU-{gen.split(':')[0]}"
        return None

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        from ray_tpu.util.accelerators import tpu as helpers

        pod = helpers.get_current_pod_name()
        if pod:
            # pod-name resource: schedule a gang onto one specific pod
            # (reference tpu.py:375 TPU-{name} affinity resource)
            return {f"TPU-{pod}": 1.0}
        return {}

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        if quantity != int(quantity):
            return (False, "TPU chip requests must be whole chips")
        return (True, None)

    @staticmethod
    def set_current_process_visible_accelerators(ids: List[str]) -> None:
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in ids)


def get_chip_topology(n_chips: int) -> Dict[int, tuple]:
    """ICI topology of this host's chips: {chip_id: (x, y) or (x, y, z)}.

    The SLICE placement strategy reserves ICI-contiguous chips; that
    needs physical coordinates, which the reference never models (its
    TPU support stops at per-pod gang resources, reference
    python/ray/_private/accelerators/tpu.py:352-375).

    Sources, in priority order:
      - ``TPU_CHIP_COORDS``: explicit "id:x,y[,z];id:x,y[,z]" (tests,
        exotic wiring),
      - ``TPU_TOPOLOGY``: "XxY" or "XxYxZ" grid, chips numbered
        row-major (the TPU VM metadata convention, e.g. v5e "2x4"),
      - chip-count defaults for single-host slices (v5e hosts carry 1,
        4, or 8 chips in 1x1 / 2x2 / 2x4 meshes).

    Returns {} when the topology is unknown — SLICE is then rejected
    rather than silently degraded.
    """
    spec = os.environ.get("TPU_CHIP_COORDS")
    if spec:
        try:
            out: Dict[int, tuple] = {}
            for part in spec.split(";"):
                part = part.strip()
                if not part:
                    continue
                cid, _, coord = part.partition(":")
                out[int(cid)] = tuple(int(c) for c in coord.split(","))
            return out
        except ValueError:
            return {}  # unknown topology; SLICE is rejected at creation
    topo = os.environ.get("TPU_TOPOLOGY")
    if not topo:
        topo = {1: "1x1", 4: "2x2", 8: "2x4"}.get(n_chips)
    if not topo:
        return {}
    try:
        dims = [int(d) for d in topo.lower().split("x")]
    except ValueError:
        return {}
    total = 1
    for d in dims:
        total *= d
    if total != n_chips:
        return {}
    coords: Dict[int, tuple] = {}
    for cid in range(n_chips):
        rem, coord = cid, []
        for d in reversed(dims):
            coord.append(rem % d)
            rem //= d
        coords[cid] = tuple(reversed(coord))
    return coords
