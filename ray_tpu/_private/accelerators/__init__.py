"""Accelerator managers (reference: python/ray/_private/accelerators/).

The registry the runtime consults at node start to detect local
accelerators, derive their resource entries, and pin visibility for
workers. TPU-first: the TPU manager is the real implementation; the ABC
matches the reference's AcceleratorManager surface so other plugins
(GPU flavors) can slot in.
"""

from .accelerator import AcceleratorManager
from .tpu import TPUAcceleratorManager

_MANAGERS = [TPUAcceleratorManager]


def get_all_accelerator_managers():
    return list(_MANAGERS)


def detect_resources() -> dict:
    """Aggregate resource entries from every manager that detects
    hardware (called by ray_tpu.init / node agents)."""
    out: dict = {}
    for mgr in _MANAGERS:
        n = mgr.get_current_node_num_accelerators()
        if n <= 0:
            continue
        out[mgr.get_resource_name()] = float(n)
        acc_type = mgr.get_current_node_accelerator_type()
        if acc_type:
            # accelerator_type + pod-name resources for gang affinity
            # (reference: tpu.py:352,375)
            out[f"accelerator_type:{acc_type}"] = 1.0
        extra = mgr.get_current_node_additional_resources()
        if extra:
            out.update(extra)
    return out


__all__ = [
    "AcceleratorManager",
    "TPUAcceleratorManager",
    "detect_resources",
    "get_all_accelerator_managers",
]
