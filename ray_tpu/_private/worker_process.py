"""Worker process: executes tasks and hosts actors.

The analogue of the reference's default_worker.py + the execution half
of CoreWorker (reference: python/ray/_private/workers/default_worker.py,
src/ray/core_worker/transport/task_receiver.h). One process executes one
task at a time; an actor pins its process for its lifetime (the
reference's WorkerPool does the same, src/ray/raylet/worker_pool.h).

Concurrency model per the reference's scheduling queues
(src/ray/core_worker/transport/):
  - plain tasks and sync actors: strict FIFO on the main executor thread
    (ActorSchedulingQueue ordering),
  - actors with max_concurrency>1: a thread pool (concurrency groups),
  - async actors (coroutine methods): a persistent asyncio event loop,
    many calls in flight (the reference runs async actors on an asyncio
    loop owned by the core worker).

TPU chip visibility: the hub assigns chip ids at dispatch; we export
TPU_VISIBLE_CHIPS before user code first imports jax (the reference's
TPUAcceleratorManager.set_current_process_visible_accelerators —
python/ray/_private/accelerators/tpu.py:193 — does the same).
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from . import profiling as _prof
from . import protocol as P
from .client import CoreClient
from .serialization import dumps_inline, loads_function, loads_inline
from ..util import tracing as _t


class _ExecTrace:
    """Runtime spans for one traced task execution (the exec payload
    carried a "trace" field — sampling decided at the CLIENT; this
    class never runs for untraced tasks). Collects monotonic stamps
    around the three worker stages (arg fetch, execute, result store),
    holds the ambient tracing context during the function body so
    nested submits and user spans stitch into the trace, and ships the
    finished spans through the worker's existing hub connection."""

    __slots__ = ("client", "trace_id", "parent", "exec_id", "t", "_tok")

    def __init__(self, client, trace):
        self.client = client
        self.trace_id, self.parent = trace[0], trace[1]
        self.exec_id = _t.new_span_id()  # parent for nested work
        self.t: Dict[str, float] = {"start": time.monotonic()}
        self._tok = None

    def stamp(self, key: str) -> None:
        self.t[key] = time.monotonic()

    def enter_exec(self) -> None:
        self.stamp("exec0")
        self._tok = _t.push_context((self.trace_id, self.exec_id))

    def exit_exec(self) -> None:
        if self._tok is not None:
            _t.pop_context(self._tok)
            self._tok = None
        self.stamp("exec1")

    def emit(self, name: str, error: Optional[str] = None,
             **extra) -> None:
        t = self.t
        recs = []
        if "args0" in t and "args1" in t:
            recs.append(_t.make_runtime_record(
                "worker.arg_fetch", "arg_fetch", self.trace_id,
                self.parent, t["args0"], t["args1"],
            ))
        if "exec0" in t:
            attrs = {"name": name, **extra}
            if error is not None:
                attrs["error"] = error
            recs.append(_t.make_runtime_record(
                "worker.execute", "execute", self.trace_id, self.parent,
                t["exec0"], t.get("exec1", time.monotonic()),
                span_id=self.exec_id, attrs=attrs,
            ))
        elif error is not None:
            # failed before the body ran (fn fetch / arg decode): the
            # error span still lands so the trace shows WHERE it died
            recs.append(_t.make_runtime_record(
                "worker.execute", "execute", self.trace_id, self.parent,
                t["start"], time.monotonic(), span_id=self.exec_id,
                attrs={"name": name, "error": error},
            ))
        if "store0" in t and "store1" in t:
            recs.append(_t.make_runtime_record(
                "worker.result_store", "result_store", self.trace_id,
                self.parent, t["store0"], t["store1"],
            ))
        try:
            for rec in recs:
                self.client.send_async(P.SPAN_RECORD, rec)
        except Exception:
            pass  # tracing must never fail the task


class WorkerRuntime:
    def __init__(self, client: CoreClient):
        self.client = client
        self.fn_cache: Dict[str, Any] = {}
        self.actor_instance: Any = None
        self.actor_id: Optional[bytes] = None
        self.actor_restarted = False
        self.actor_pg: Optional[tuple] = None  # (pg_id, bundle_idx)
        self.pool: Optional[ThreadPoolExecutor] = None
        self.aio_loop: Optional[asyncio.AbstractEventLoop] = None

    # ----------------------------------------------------------- arg decode
    def _decode_args(self, args_kind: str, args_payload: Any):
        if args_kind == "inline":
            args, kwargs = loads_inline(args_payload)
        else:  # "ref": oversized arg tuple was spilled to the object store
            from .ids import ObjectID

            args, kwargs = self.client.get([ObjectID(args_payload)])[0]
        args = tuple(self._resolve(a) for a in args)
        kwargs = {k: self._resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    def _resolve(self, v):
        from ..object_ref import ObjectRef

        if isinstance(v, ObjectRef):
            return self.client.get([v._id])[0]
        return v

    def _get_fn(self, fn_id: str, fn_blob):
        fn = self.fn_cache.get(fn_id)
        if fn is None:
            if fn_blob is None:
                reply = self.client.request(P.GET_FUNCTION, {"fn_id": fn_id})
                fn_blob = reply["blob"]
            fn = loads_function(fn_blob)
            self.fn_cache[fn_id] = fn
        return fn

    def _store_returns(self, return_ids, result, num_expected):
        from .ids import ObjectID

        if num_expected == 1:
            values = [result]
        elif num_expected == 0:
            values = []
        else:
            values = list(result)
            if len(values) != num_expected:
                raise ValueError(
                    f"task declared num_returns={num_expected} but returned {len(values)} values"
                )
        out = []
        for oid_bytes, val in zip(return_ids, values):
            kind, payload, size = self.client.encode_value(ObjectID(oid_bytes), val)
            out.append((oid_bytes, kind, payload, size))
        return out

    def _error_returns(self, return_ids, fn_name: str):
        from ..exceptions import TaskCancelledError, TaskError

        tb = traceback.format_exc()
        exc_type, exc, _ = sys.exc_info()
        if exc_type is KeyboardInterrupt:
            # hub-sent SIGINT = cooperative cancellation (ray.cancel)
            err: Exception = TaskCancelledError("task was cancelled")
        else:
            # keep the original exception as the cause (retry_exceptions
            # type filters and user handlers match on it); fall back to
            # cause=None when it does not pickle
            err = TaskError(fn_name, tb, cause=exc)
        try:
            blob = dumps_inline(err)
        except Exception:
            try:
                err = TaskError(fn_name, tb, cause=None)
                blob = dumps_inline(err)
            except Exception:
                blob = dumps_inline(TaskError(fn_name, tb))
        return [(oid, P.VAL_ERROR, blob, 0) for oid in return_ids]

    def _stream_yield_one(self, p: dict, value) -> None:
        from .ids import ObjectID

        oid = ObjectID.generate()
        kind, payload, size = self.client.encode_value(oid, value)
        self.client.send(
            P.STREAM_YIELD,
            {
                "task_id": p["task_id"],
                "object_id": oid.binary(),
                "kind": kind,
                "payload": payload,
                "size": size,
            },
        )

    def _stream_results(self, p: dict, gen) -> None:
        """Drive a generator task: yield values become incremental stream
        objects (reference: streaming generator protocol, the worker
        reports each return as it is produced, _raylet.pyx:280). The
        TASK_DONE at the end frees the worker; the stream itself ends via
        STREAM_END (error carried as the stream's final object)."""
        task_id = p["task_id"]
        bp = (p.get("options") or {}).get("_generator_backpressure_num_objects")
        try:
            idx = 0
            for value in gen:
                self._stream_yield_one(p, value)
                idx += 1
                if bp and idx >= bp:
                    # wait until the consumer is within the window
                    self.client.request(
                        P.STREAM_CREDIT,
                        {"task_id": task_id, "min_consumed": idx - bp + 1},
                    )
            self.client.send(P.STREAM_END, {"task_id": task_id, "error": None})
        except Exception:
            from ..exceptions import TaskError

            err = TaskError("streaming_generator", traceback.format_exc())
            self.client.send(
                P.STREAM_END, {"task_id": task_id, "error": dumps_inline(err)}
            )
        self.client.send(P.TASK_DONE, {"task_id": task_id, "returns": []})

    def _adopt_job_identity(self, p: dict) -> None:
        """Inherit the submitting job's scheduling identity (fairsched
        tenant/priority/job_id, forwarded in the exec options) so
        NESTED submits from inside this task are stamped with it —
        quota admission and fair-share accounting must not be escapable
        by fanning work out through a worker. Context-local, not client
        fields: a max_concurrency actor serves different tenants
        concurrently, and caller A's nested submits must never carry
        caller B's identity."""
        from .client import _job_identity

        opts = p.get("options") or {}
        try:
            priority = int(opts.get("priority") or 0)
        except (TypeError, ValueError):
            priority = 0
        _job_identity.set(
            (opts.get("job_id"), opts.get("tenant"), priority)
        )

    def _chaos_stall(self) -> None:
        """Fault injection (chaos.py, "worker" scope): a
        ``delay:worker.exec@lo-hi`` rule stalls the task body before it
        runs — an in-worker slow-execute fault that needs no signals
        (the SIGSTOP-style stall is the hub's worker_hang). Inert (one
        attribute load) without a plan."""
        eng = self.client._chaos
        if eng is not None:
            act = eng.message_action("exec")
            if act is not None and act[0] == "delay":
                time.sleep(act[1])

    # ------------------------------------------------------------ execution
    def exec_task(self, p: dict):
        self._adopt_job_identity(p)
        self._chaos_stall()
        if p.get("tpu_chips"):
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in p["tpu_chips"])
        from ..runtime_context import _current_pg

        pg = (p.get("options") or {}).get("placement_group")
        _current_pg.set(tuple(pg) if pg else None)
        fn_name = p["fn_id"]
        tr = p.get("trace")
        et = _ExecTrace(self.client, tr) if tr is not None else None
        try:
            fn = self._get_fn(p["fn_id"], p.get("fn_blob"))
            fn_name = getattr(fn, "__name__", fn_name)
            if et is not None:
                et.stamp("args0")
            args, kwargs = self._decode_args(p["args_kind"], p["args_payload"])
            if et is not None:
                et.stamp("args1")
                et.enter_exec()
            try:
                result = fn(*args, **kwargs)
            finally:
                if et is not None:
                    et.exit_exec()
            if (p.get("options") or {}).get("streaming"):
                if et is not None:
                    # the generator body runs lazily inside
                    # _stream_results; the execute span here covers
                    # only its construction
                    et.emit(fn_name, streaming=True)
                self._stream_results(p, result)
                return
            if et is not None:
                et.stamp("store0")
            returns = self._store_returns(p["return_ids"], result, len(p["return_ids"]))
            if et is not None:
                et.stamp("store1")
                et.emit(fn_name)
        except (Exception, KeyboardInterrupt):
            if et is not None:
                et.exit_exec()
                et.emit(fn_name, error=sys.exc_info()[0].__name__)
            if (p.get("options") or {}).get("streaming"):
                # failed before the generator started: the stream (not
                # return objects) carries the error
                self._stream_fail(p, fn_name)
                return
            returns = self._error_returns(p["return_ids"], fn_name)
        self._send_done({"task_id": p["task_id"], "returns": returns})

    def _send_done(self, payload: dict) -> None:
        """TASK_DONE with load-adaptive batching: while more work is
        queued, completions ride the async buffer (the next send — or
        the flusher — coalesces them into one hub message); when the
        queue is empty, send immediately for latency. send() flushes
        the buffer first, so completion order is preserved."""
        if self.client.task_queue.qsize() > 0:
            self.client.send_async(P.TASK_DONE, payload)
        else:
            self.client.send(P.TASK_DONE, payload)

    def reply_cancelled(self, p: dict) -> None:
        # the reader thread already resolved the caller (CANCEL_TASK
        # fast path); dequeue just discards the stale assignment
        self.client.cancelled_tasks.discard(p["task_id"])

    def _stream_fail(self, p: dict, name: str) -> None:
        from ..exceptions import TaskError

        err = TaskError(name, traceback.format_exc())
        self.client.send(
            P.STREAM_END, {"task_id": p["task_id"], "error": dumps_inline(err)}
        )
        self.client.send(P.TASK_DONE, {"task_id": p["task_id"], "returns": []})

    def exec_actor_create(self, p: dict):
        self._adopt_job_identity(p)
        if p.get("tpu_chips"):
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in p["tpu_chips"])
        # the hub marks respawned incarnations so user __init__ can
        # branch on was_current_actor_reconstructed; always assigned so
        # a later actor on a reused worker never inherits the flag
        self.actor_restarted = bool((p.get("options") or {}).get("_restarted"))
        from ..runtime_context import _current_pg

        pg = (p.get("options") or {}).get("placement_group")
        self.actor_pg = tuple(pg) if pg else None
        _current_pg.set(self.actor_pg)
        try:
            cls = self._get_fn(p["fn_id"], p.get("fn_blob"))
            args, kwargs = self._decode_args(p["args_kind"], p["args_payload"])
            self.actor_instance = cls(*args, **kwargs)
            self.actor_id = p["actor_id"]
            maxc = (p.get("options") or {}).get("max_concurrency") or 1
            if maxc > 1:
                self.pool = ThreadPoolExecutor(max_workers=maxc)
            self.client.send(P.ACTOR_READY, {"actor_id": p["actor_id"], "error": None})
        except Exception:
            from ..exceptions import TaskError

            err = TaskError(p["fn_id"], traceback.format_exc())
            self.client.send(
                P.ACTOR_READY, {"actor_id": p["actor_id"], "error": dumps_inline(err)}
            )

    def _run_actor_method(self, p: dict):
        # pool threads don't inherit the main loop's contextvars: pin
        # the task id (and the caller's job identity, for nested
        # submits) here so get_runtime_context() and fairsched stamping
        # work under max_concurrency > 1
        from ..runtime_context import _current_pg, _current_task_id

        _current_task_id.set(p.get("task_id"))
        if _prof._ACTIVE:  # sample attribution for pool threads
            _prof.set_task(p.get("task_id"))
        _current_pg.set(getattr(self, "actor_pg", None))
        self._adopt_job_identity(p)
        self._chaos_stall()
        method_name = p["method"]
        tr = p.get("trace")
        et = _ExecTrace(self.client, tr) if tr is not None else None
        try:
            if method_name == "__ray_ready__":
                result = None
            elif method_name == "__ray_terminate__":
                self.client.send(
                    P.TASK_DONE,
                    {
                        "task_id": p["task_id"],
                        "returns": self._store_returns(p["return_ids"], None, len(p["return_ids"])),
                    },
                )
                os._exit(0)
            elif method_name == "__ray_call__":
                # run an arbitrary callable against the actor instance
                # (reference: ray's ActorHandle.__ray_call__)
                args, kwargs = self._decode_args(p["args_kind"], p["args_payload"])
                fn, rest = args[0], args[1:]
                result = fn(self.actor_instance, *rest, **kwargs)
            else:
                method = getattr(self.actor_instance, method_name)
                if et is not None:
                    et.stamp("args0")
                args, kwargs = self._decode_args(p["args_kind"], p["args_payload"])
                if et is not None:
                    et.stamp("args1")
                    et.enter_exec()
                try:
                    result = method(*args, **kwargs)
                finally:
                    if et is not None:
                        et.exit_exec()
            if (p.get("options") or {}).get("streaming"):
                if et is not None:
                    et.emit(method_name, streaming=True)
                self._stream_results(p, result)
                return
            if et is not None:
                et.stamp("store0")
            returns = self._store_returns(p["return_ids"], result, len(p["return_ids"]))
            if et is not None:
                et.stamp("store1")
                et.emit(method_name)
        except Exception:
            if et is not None:
                et.exit_exec()
                et.emit(method_name, error=sys.exc_info()[0].__name__)
            if (p.get("options") or {}).get("streaming"):
                self._stream_fail(p, method_name)
                return
            returns = self._error_returns(p["return_ids"], method_name)
        self._send_done({"task_id": p["task_id"], "returns": returns})

    def _ensure_aio_loop(self):
        if self.aio_loop is None:
            self.aio_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self.aio_loop.run_forever, daemon=True, name="actor-aio")
            t.start()
        return self.aio_loop

    def exec_actor_task(self, p: dict):
        self._adopt_job_identity(p)
        import inspect

        method = getattr(type(self.actor_instance), p["method"], None) if p["method"] not in (
            "__ray_ready__",
            "__ray_terminate__",
        ) else None
        if (
            method is not None
            and inspect.isasyncgenfunction(method)
            and (p.get("options") or {}).get("streaming")
        ):
            loop = self._ensure_aio_loop()

            async def run_stream():
                try:
                    args, kwargs = self._decode_args(p["args_kind"], p["args_payload"])
                    agen = method(self.actor_instance, *args, **kwargs)
                    items = []
                    async for v in agen:
                        items.append(v)
                        # flush incrementally: one yield per item keeps
                        # streaming semantics without a sync bridge
                        self._stream_yield_one(p, v)
                    self.client.send(
                        P.STREAM_END, {"task_id": p["task_id"], "error": None}
                    )
                except Exception:
                    from ..exceptions import TaskError

                    err = TaskError(p["method"], traceback.format_exc())
                    self.client.send(
                        P.STREAM_END,
                        {"task_id": p["task_id"], "error": dumps_inline(err)},
                    )
                self.client.send(
                    P.TASK_DONE, {"task_id": p["task_id"], "returns": []}
                )

            asyncio.run_coroutine_threadsafe(run_stream(), loop)
        elif method is not None and asyncio.iscoroutinefunction(method):
            loop = self._ensure_aio_loop()

            async def run():
                # coroutines interleave on the one aio thread, so the
                # thread-keyed register is last-writer-wins: a sample
                # lands on whichever call most recently resumed — the
                # one holding the loop between awaits, which is the one
                # burning the CPU being sampled
                if _prof._ACTIVE:
                    _prof.set_task(p.get("task_id"))
                tr = p.get("trace")
                et = _ExecTrace(self.client, tr) if tr is not None else None
                try:
                    if et is not None:
                        et.stamp("args0")
                    args, kwargs = self._decode_args(p["args_kind"], p["args_payload"])
                    if et is not None:
                        et.stamp("args1")
                        et.enter_exec()
                    try:
                        result = await method(self.actor_instance, *args, **kwargs)
                    finally:
                        if et is not None:
                            et.exit_exec()
                    if et is not None:
                        et.stamp("store0")
                    returns = self._store_returns(p["return_ids"], result, len(p["return_ids"]))
                    if et is not None:
                        et.stamp("store1")
                        et.emit(p["method"])
                except Exception:
                    if et is not None:
                        et.exit_exec()
                        et.emit(p["method"], error=sys.exc_info()[0].__name__)
                    returns = self._error_returns(p["return_ids"], p["method"])
                self._send_done({"task_id": p["task_id"], "returns": returns})

            asyncio.run_coroutine_threadsafe(run(), loop)
        elif self.pool is not None:
            self.pool.submit(self._run_actor_method, p)
        else:
            self._run_actor_method(p)


def _setup_runtime_env(client, session_dir: str) -> None:
    """Materialize this worker's runtime env (reference: the runtime-env
    agent's env-context application, runtime_env_agent.py:303): env_vars
    into the process env; working_dir fetched by URI from the cluster KV
    once per content hash (cached extract dir) then chdir + sys.path."""
    import json

    renv_json = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if not renv_json:
        return
    renv = json.loads(renv_json)
    for k, v in (renv.get("env_vars") or {}).items():
        os.environ[k] = v
    # conda was handled pre-connect in main() (execv re-entry)
    if renv.get("pip"):
        _materialize_pip_env(client, session_dir, renv["pip"])
    for mod_uri in renv.get("py_modules") or ():
        # reference: py_modules.py — one cached extract dir per content
        # hash, prepended to sys.path (no chdir, unlike working_dir)
        target = os.path.join(session_dir, "runtime_envs", f"pymod_{mod_uri}")
        if not os.path.isdir(target):
            blob = client.kv_get(f"__runtime_env_pkg__{mod_uri}".encode())
            if blob is None:
                raise RuntimeError(
                    f"runtime env py_module {mod_uri} missing from KV"
                )
            import io
            import zipfile

            tmp = target + f".tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, target)
            except OSError:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        sys.path.insert(0, target)
    uri = renv.get("working_dir_uri")
    if uri:
        import zipfile

        target = os.path.join(session_dir, "runtime_envs", uri)
        if not os.path.isdir(target):
            blob = client.kv_get(f"__runtime_env_pkg__{uri}".encode())
            if blob is None:
                raise RuntimeError(f"runtime env package {uri} missing from KV")
            tmp = target + f".tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            import io

            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, target)
            except OSError:
                # another worker won the race; use its copy
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        os.chdir(target)
        sys.path.insert(0, target)


def _materialize_conda_env(spec: dict) -> None:
    """Re-exec this worker inside a conda env (reference:
    _private/runtime_env/conda.py — get_or_create_conda_env + the
    context's python override). Named envs resolve directly; dict specs
    materialize once per content hash under the conda root, guarded by
    the same create-exclusive lock pattern as the pip cache. Requires a
    conda/mamba/micromamba binary (RAY_TPU_CONDA_EXE, CONDA_EXE, or
    PATH) — absent tooling fails loudly at task dispatch, matching the
    reference's behavior when conda is not installed."""
    import hashlib
    import json as _json
    import shutil
    import subprocess
    import time

    if os.environ.get("RAY_TPU_IN_CONDA_ENV"):
        return  # already re-exec'd inside the target env
    exe = os.environ.get("RAY_TPU_CONDA_EXE") or os.environ.get("CONDA_EXE")
    if not exe:
        for cand in ("conda", "mamba", "micromamba"):
            exe = shutil.which(cand)
            if exe:
                break
    if not exe:
        raise RuntimeError(
            "runtime_env conda requires a conda/mamba/micromamba binary "
            "(set RAY_TPU_CONDA_EXE or install one); none found on PATH"
        )
    if spec.get("name"):
        # named env: resolve its prefix via conda itself
        out = subprocess.run(
            [exe, "env", "list", "--json"], capture_output=True, text=True,
            timeout=60,
        )
        envs = _json.loads(out.stdout or "{}").get("envs", [])
        prefix = next(
            (e for e in envs if os.path.basename(e) == spec["name"]), None
        )
        if prefix is None:
            raise RuntimeError(f"conda env {spec['name']!r} not found")
    else:
        blob = _json.dumps(spec["spec"], sort_keys=True).encode()
        env_id = hashlib.sha1(blob).hexdigest()[:16]
        root = os.environ.get(
            "RAY_TPU_CONDA_ENV_ROOT",
            os.path.join(os.path.expanduser("~"), ".ray_tpu_conda_envs"),
        )
        prefix = os.path.join(root, env_id)
        done = os.path.join(prefix, ".create_done")
        if not os.path.exists(done):
            os.makedirs(root, exist_ok=True)
            lock = os.path.join(root, f"{env_id}.lock")
            deadline = time.monotonic() + 1800
            acquired = False
            while time.monotonic() < deadline:
                try:
                    fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                    acquired = True
                    break
                except FileExistsError:
                    if os.path.exists(done):
                        break
                    time.sleep(0.5)
            if acquired:
                try:
                    if not os.path.exists(done):
                        spec_file = os.path.join(root, f"{env_id}.yml")
                        with open(spec_file, "w") as f:
                            _json.dump(spec["spec"], f)
                        proc = subprocess.run(
                            [exe, "env", "create", "--prefix", prefix,
                             "--file", spec_file, "--json"],
                            capture_output=True, text=True, timeout=1700,
                        )
                        if proc.returncode != 0:
                            # a partial prefix poisons every retry
                            # (conda refuses an existing non-empty dir)
                            shutil.rmtree(prefix, ignore_errors=True)
                            raise RuntimeError(
                                f"conda env create failed:\n{proc.stderr}"
                            )
                        with open(done, "w") as f:
                            f.write(env_id)
                finally:
                    try:
                        os.unlink(lock)
                    except OSError:
                        pass
            if not os.path.exists(done):
                raise RuntimeError(
                    f"conda env create did not complete for {env_id}"
                )
    env_python = os.path.join(prefix, "bin", "python")
    if not os.path.exists(env_python):
        raise RuntimeError(f"conda env at {prefix} has no python")
    # the env's interpreter must also see ray_tpu itself
    os.environ["RAY_TPU_IN_CONDA_ENV"] = prefix
    os.environ["PYTHONPATH"] = os.pathsep.join(
        dict.fromkeys(
            [os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep)
        )
    ).rstrip(os.pathsep)
    os.execv(env_python, [env_python, "-m", "ray_tpu._private.worker_process"])


def _materialize_pip_env(client, session_dir: str, spec: dict) -> None:
    """Install the env's requirements into a per-node content-hash
    cached directory and prepend it to sys.path (reference:
    _private/runtime_env/pip.py virtualenv build + uri_cache.py; here
    the interpreter is shared, so isolation is an import-path overlay
    rather than a separate venv — workers only serve matching
    runtime_env hashes, so cross-env leakage cannot happen).

    Shipped wheels install offline (--no-index --find-links on the KV
    fetch dir); plain requirements go to the configured index and fail
    loudly without egress."""
    import hashlib
    import json as _json
    import subprocess
    import time

    key = _json.dumps(spec, sort_keys=True).encode()
    env_id = hashlib.sha1(key).hexdigest()[:16]
    base = os.path.join(session_dir, "runtime_envs")
    target = os.path.join(base, f"pip_{env_id}")
    done = os.path.join(target, ".install_done")
    if not os.path.exists(done):
        os.makedirs(base, exist_ok=True)
        lock = os.path.join(base, f"pip_{env_id}.lock")
        acquired = False
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                acquired = True
                break
            except FileExistsError:
                if os.path.exists(done):
                    break  # another worker finished the install
                try:
                    # break locks orphaned by a killed installer; the
                    # atomic rename means exactly one waiter wins the
                    # break (unlink-by-path could kill a FRESH lock)
                    if time.time() - os.path.getmtime(lock) > 300:
                        claimed = f"{lock}.stale.{os.getpid()}"
                        os.rename(lock, claimed)
                        os.unlink(claimed)
                        continue
                except OSError:
                    continue  # lock vanished or another waiter won
                time.sleep(0.2)
        if acquired:
            try:
                if not os.path.exists(done):
                    args = [sys.executable, "-m", "pip", "install",
                            "--quiet", "--no-warn-script-location",
                            "--target", target]
                    wheels = spec.get("wheels") or {}  # uri -> filename
                    wheel_paths = []
                    for uri, fname in wheels.items():
                        blob = client.kv_get(
                            f"__runtime_env_whl__{uri}".encode()
                        )
                        if blob is None:
                            raise RuntimeError(
                                f"runtime env wheel {fname} missing from KV"
                            )
                        # one subdir per content hash: same-named wheels
                        # with different contents cannot collide
                        wdir = os.path.join(target, ".wheels", uri)
                        os.makedirs(wdir, exist_ok=True)
                        wpath = os.path.join(wdir, fname)
                        with open(wpath, "wb") as f:
                            f.write(blob)
                        wheel_paths.append(wpath)
                    # every wheel dir is a findable index so a shipped
                    # wheel can satisfy another shipped wheel's
                    # dependency; wheels-only installs are fully offline
                    for wpath in wheel_paths:
                        args += ["--find-links", os.path.dirname(wpath)]
                    if wheels and not spec.get("reqs"):
                        args += ["--no-index"]
                    args += list(spec.get("reqs") or [])
                    args += wheel_paths
                    proc = subprocess.run(
                        args, capture_output=True, text=True, timeout=280
                    )
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"runtime_env pip install failed:\n{proc.stderr}"
                        )
                    with open(done, "w") as f:
                        f.write(env_id)
            finally:
                try:
                    os.unlink(lock)
                except OSError:
                    pass
        if not os.path.exists(done):
            raise RuntimeError(
                f"runtime_env pip install did not complete for {env_id}"
            )
    sys.path.insert(0, target)


class _LogTee:
    """Mirror worker stdout/stderr to the driver (reference: worker log
    redirection + log_monitor.py streaming to the driver). Lines batch
    through the existing hub connection; the original stream still gets
    everything (container logs)."""

    def __init__(self, client, orig, stream_name: str):
        self._client = client
        self._orig = orig
        self._name = stream_name
        self._buf = ""
        self._lock = threading.Lock()

    def _emit(self, lines):
        lines = [l for l in lines if l.strip()]
        if lines:
            try:
                self._client.send_async(
                    P.LOG_RECORD,
                    {"stream": self._name, "lines": lines,
                     "pid": os.getpid()},
                )
            except Exception:
                pass

    def write(self, s):
        self._orig.write(s)
        with self._lock:  # concurrent print()s must not corrupt the buffer
            self._buf += s
            if "\n" not in self._buf:
                return len(s)
            *lines, self._buf = self._buf.split("\n")
        self._emit(lines)
        return len(s)

    def flush(self):
        self._orig.flush()
        with self._lock:
            tail, self._buf = self._buf, ""
        if tail:
            self._emit([tail])

    def __getattr__(self, name):
        return getattr(self._orig, name)


def main():
    sys.setswitchinterval(0.001)
    hub_addr = os.environ["RAY_TPU_HUB_ADDR"]
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    # conda re-exec must happen BEFORE the hub connection exists: execv
    # closes the socket (CLOEXEC) and the replacement process redoes
    # HELLO — connecting first would surface as a spurious worker death.
    # Materialization failures are RECORDED, not raised: the worker
    # still connects and fails its tasks with the setup error
    # (reference: RuntimeEnvSetupError delivered to the task), instead
    # of dying pre-connect and triggering a respawn storm.
    setup_error: Optional[Exception] = None
    renv_json = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if renv_json:
        import json as _json

        conda_spec = _json.loads(renv_json).get("conda")
        if conda_spec:
            try:
                _materialize_conda_env(conda_spec)  # may not return (execv)
            except Exception as e:  # noqa: BLE001
                setup_error = e
    client = CoreClient(hub_addr, session_dir, role="worker", worker_id=worker_id)
    if setup_error is None:
        try:
            _setup_runtime_env(client, session_dir)
        except Exception as e:  # noqa: BLE001
            setup_error = e
    if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0":
        sys.stdout = _LogTee(client, sys.stdout, "stdout")
        sys.stderr = _LogTee(client, sys.stderr, "stderr")

    # make ray_tpu.* API work inside tasks (auto-connect)
    from . import worker as worker_mod

    worker_mod._set_global_client(client)

    rt = WorkerRuntime(client)
    worker_mod._worker_runtime = rt  # get_runtime_context() actor ids

    from ..runtime_context import _current_task_id

    while True:
        try:
            msg_type, payload = client.task_queue.get()
            if isinstance(payload, dict) and "task_id" in payload:
                _current_task_id.set(payload["task_id"])
                if _prof._ACTIVE:  # sample attribution (profiler on)
                    _prof.set_task(payload["task_id"])
            if msg_type == P.KILL:
                # a just-finished task's TASK_DONE may still sit in the
                # async send buffer (_send_done batching) — flush so the
                # hub never retries a task that already completed
                try:
                    client.flush()
                except Exception:
                    pass
                os._exit(0)
            elif msg_type in (P.EXEC_TASK, P.EXEC_ACTOR_TASK) and (
                payload["task_id"] in client.cancelled_tasks
            ):
                rt.reply_cancelled(payload)
            elif setup_error is not None and msg_type in (
                P.EXEC_TASK, P.EXEC_ACTOR_TASK, P.EXEC_ACTOR_CREATE,
            ):
                # runtime env never materialized: every task fails with
                # the setup error (reference: RuntimeEnvSetupError)
                from ..exceptions import TaskError

                err = TaskError(
                    "runtime_env_setup",
                    f"runtime env setup failed: {setup_error}",
                    cause=setup_error,
                )
                blob = dumps_inline(err)
                returns = [
                    (oid, P.VAL_ERROR, blob, 0)
                    for oid in payload.get("return_ids", [])
                ]
                if msg_type == P.EXEC_ACTOR_CREATE:
                    client.send(P.ACTOR_READY, {
                        "actor_id": payload["actor_id"], "error": blob,
                    })
                else:
                    if (payload.get("options") or {}).get("streaming"):
                        # generator callers wait on the STREAM, not the
                        # (empty) return ids
                        client.send(P.STREAM_END, {
                            "task_id": payload["task_id"], "error": blob,
                        })
                    client.send(P.TASK_DONE, {
                        "task_id": payload["task_id"], "returns": returns,
                    })
            elif msg_type == P.EXEC_TASK:
                rt.exec_task(payload)
            elif msg_type == P.EXEC_ACTOR_CREATE:
                rt.exec_actor_create(payload)
            elif msg_type == P.EXEC_ACTOR_TASK:
                rt.exec_actor_task(payload)
        except KeyboardInterrupt:
            # cancellation SIGINT landed between tasks: stay alive
            continue


if __name__ == "__main__":
    main()
