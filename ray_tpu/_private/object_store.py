"""Node-local shared-memory object store.

TPU-native re-think of the reference's plasma store (reference:
src/ray/object_manager/plasma/ — dlmalloc arena over mmap/shm, fd passing
via fling.cc, flatbuffer protocol). We get the same zero-copy property
with far less machinery by backing each large object with an mmap'ed
file under /dev/shm/<session>/ that every process on the node can map.
There is no socket protocol: object *placement* metadata lives in the
control hub; the bytes themselves are mapped directly.

Small objects (< INLINE_THRESHOLD, like the reference's
max_direct_call_object_size=100KB, reference: src/ray/common/
ray_config_def.h) never touch shm — they travel inline through the hub,
mirroring the reference's in-process CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h:45).

Wire layout of a segment:
    [8B u64 header_len][header bytes]
    per out-of-band buffer: [8B u64 buf_len][pad to 64B][buf bytes][pad]
Buffers are 64-byte aligned so numpy views are alignment-friendly.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import threading
from typing import Any, List, Optional, Tuple

from . import serialization

INLINE_THRESHOLD = 100 * 1024  # match reference max_direct_call_object_size
_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class MappedSegment:
    """An open mmap of one object segment; kept alive while views exist.
    Segments are WRITTEN with sequential os.write (put_raw) — this class
    only opens and maps existing files for readers."""

    __slots__ = ("path", "mm", "size")

    def __init__(self, path: str):
        self.path = path
        fd = os.open(path, os.O_RDWR)
        try:
            st = os.fstat(fd)
            self.mm = mmap.mmap(fd, st.st_size)
        finally:
            os.close(fd)
        self.size = st.st_size

    @classmethod
    def from_fd(cls, path: str, fd: int, size: int) -> "MappedSegment":
        """Map the WRITER'S OWN fd (before close): re-opening by path
        could observe a concurrent rewriter's fresh, incomplete file
        (speculative task retry of the same object id)."""
        seg = cls.__new__(cls)
        seg.path = path
        seg.mm = mmap.mmap(fd, size)
        seg.size = size
        return seg


def _write_all(fd: int, data) -> None:
    """write() can return short (and caps at ~2 GiB per call) — loop."""
    view = memoryview(data)
    written = 0
    while written < view.nbytes:
        written += os.write(fd, view[written:])


class ShmObjectStore:
    """Per-process facade over the node's /dev/shm session directory."""

    def __init__(self, session_dir: str):
        self.dir = os.path.join(session_dir, "objects")
        os.makedirs(self.dir, exist_ok=True)
        self._segments: dict[str, MappedSegment] = {}
        self._lock = threading.Lock()

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def put(self, name: str, obj: Any) -> int:
        """Serialize obj into a new segment. Returns segment size."""
        header, buffers = serialization.dumps_oob(obj)
        return self.put_raw(name, header, [b.raw() for b in buffers])

    def put_raw(self, name: str, header: bytes, raws: List[memoryview]) -> int:
        """Write a segment from pre-serialized (header, buffers).

        Sequential os.write, NOT mmap assignment: writing through a
        fresh mmap faults one page at a time (~1.3 GiB/s on this class
        of host) while write() bulk-copies in the kernel (~2.9 GiB/s —
        the raw tmpfs ceiling). The segment is only mmap'd by readers."""
        path = self._path(name)
        # a retried task may rewrite the same object id; the old segment
        # stays valid for existing mmaps after the unlink
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        size = 0
        try:
            parts: List[bytes] = [struct.pack("<Q", len(header)), header]
            pos = 8 + len(header)
            for r in raws:
                pad_to = _align(pos)
                if pad_to != pos:
                    parts.append(b"\x00" * (pad_to - pos))
                    pos = pad_to
                parts.append(struct.pack("<Q", r.nbytes))
                pos += 8
                pad_to = _align(pos)
                if pad_to != pos:
                    parts.append(b"\x00" * (pad_to - pos))
                    pos = pad_to
                # flush small parts, then bulk-write the buffer itself
                _write_all(fd, b"".join(parts))
                parts = []
                _write_all(
                    fd, r.cast("B") if r.format != "B" or r.ndim != 1 else r
                )
                pos += r.nbytes
            pad_to = _align(pos)
            if pad_to != pos:
                parts.append(b"\x00" * (pad_to - pos))
                pos = pad_to
            if parts:
                _write_all(fd, b"".join(parts))
            size = pos
            seg = MappedSegment.from_fd(path, fd, size)
        finally:
            os.close(fd)
        with self._lock:
            self._segments[name] = seg
        return size

    def get(self, name: str) -> Any:
        """Map the segment and deserialize zero-copy (buffers view the mmap)."""
        with self._lock:
            seg = self._segments.get(name)
            if seg is None:
                seg = MappedSegment(self._path(name))
                self._segments[name] = seg
        mm = seg.mm
        view = memoryview(mm)
        (hlen,) = struct.unpack_from("<Q", mm, 0)
        header = bytes(view[8 : 8 + hlen])
        off = _align(8 + hlen)
        buffers: List[memoryview] = []
        while off < seg.size:
            (blen,) = struct.unpack_from("<Q", mm, off)
            off = _align(off + 8)
            buffers.append(view[off : off + blen])
            off = _align(off + blen)
        return serialization.loads_oob(header, buffers)

    def write_segment(self, name: str, data: bytes) -> None:
        """Install a segment fetched from another node (byte-identical
        copy of the producer's file; get() then maps it locally). The
        tmp name is per-process: concurrent fetchers of the same object
        must not race each other's os.replace."""
        path = self._path(name)
        tmp = f"{path}.fetch.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def contains(self, name: str) -> bool:
        return name in self._segments or os.path.exists(self._path(name))

    def free(self, name: str) -> None:
        with self._lock:
            seg = self._segments.pop(name, None)
        # The mmap stays valid for existing views even after unlink.
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass

    def estimate_size(self, obj: Any) -> int:
        """Cheap size probe used to pick inline vs shm path."""
        try:
            import numpy as np

            if isinstance(obj, np.ndarray):
                return obj.nbytes
        except Exception:
            pass
        return -1  # unknown; caller serializes and checks
