"""Node-local shared-memory object store.

TPU-native re-think of the reference's plasma store (reference:
src/ray/object_manager/plasma/ — dlmalloc arena over mmap/shm, fd passing
via fling.cc, flatbuffer protocol). We get the same zero-copy property
with far less machinery by backing each large object with an mmap'ed
file under /dev/shm/<session>/ that every process on the node can map.
There is no socket protocol: object *placement* metadata lives in the
control hub; the bytes themselves are mapped directly.

Small objects (< INLINE_THRESHOLD, like the reference's
max_direct_call_object_size=100KB, reference: src/ray/common/
ray_config_def.h) never touch shm — they travel inline through the hub,
mirroring the reference's in-process CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h:45).

Wire layout of a segment:
    [8B u64 total_layout_size][8B u64 header_len][header bytes]
    per out-of-band buffer: [8B u64 buf_len][pad to 64B][buf bytes][pad]
Buffers are 64-byte aligned so numpy views are alignment-friendly.
The leading total word makes the layout SELF-TERMINATING: a segment
carved from a larger recycled/prewarmed pool file needs no exact-size
truncate (which frees the warm tail pages this pool exists to keep) —
readers parse to `total` and ignore any slack tail, and the word rides
along byte-identically through chunked streams and cross-node copies.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import threading
import uuid
from typing import Any, List, Optional, Tuple

from . import serialization

INLINE_THRESHOLD = 100 * 1024  # match reference max_direct_call_object_size
_ALIGN = 64

# Freed writer segments are recycled instead of unlinked: a put into
# already-faulted tmpfs pages is a plain memcpy (~7 GiB/s on one core
# here) while a fresh file pays page allocation + zeroing (~2.4 GiB/s).
# This is the same trick plasma gets from its pre-mmap'd dlmalloc arena
# (reference: src/ray/object_manager/plasma/ — the arena is faulted once
# and objects recycle its pages).
_POOL_MAX_BYTES = int(
    os.environ.get("RAY_TPU_SEGMENT_POOL_BYTES", str(2 * 1024**3))
)
_POOL_MAX_SEGMENTS = 8


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _advise_hugepage(mm: mmap.mmap) -> None:
    """Best-effort THP hint: on hosts with shmem THP enabled
    (/sys/kernel/mm/transparent_hugepage/shmem_enabled = advise) this
    roughly halves large-copy TLB pressure; everywhere else it's a
    no-op. Never fatal."""
    try:
        mm.madvise(mmap.MADV_HUGEPAGE)
    except (AttributeError, OSError, ValueError):
        pass


def _parse_segment(view: memoryview, cap: int) -> Tuple[bytes, List[memoryview]]:
    """Parse the put_raw wire layout out of `view` (a mapped segment or
    a pulled byte blob): returns (header, buffers) with every buffer a
    zero-copy sub-view of `view`. `cap` bounds the self-reported total
    so a truncated/padded source never reads past the real bytes."""
    (total,) = struct.unpack_from("<Q", view, 0)
    if not 16 <= total <= cap:
        total = cap  # defensive: never read past the mapping
    (hlen,) = struct.unpack_from("<Q", view, 8)
    header = bytes(view[16 : 16 + hlen])
    off = _align(16 + hlen)
    buffers: List[memoryview] = []
    while off < total:
        (blen,) = struct.unpack_from("<Q", view, off)
        off = _align(off + 8)
        buffers.append(view[off : off + blen])
        off = _align(off + blen)
    return header, buffers


def decode_segment_bytes(data) -> Any:
    """Deserialize a whole segment pulled as one byte blob WITHOUT
    installing it in any store — buffers stay views over `data`. This
    is the lightweight consumer path for one-shot serve payload pulls
    (object_agent.pull_segment_bytes): no store file, no replica
    registration, no ref-count bookkeeping. The caller must keep the
    returned value (its views pin `data`) alive only as long as needed."""
    view = data if isinstance(data, memoryview) else memoryview(data)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    header, buffers = _parse_segment(view, view.nbytes)
    return serialization.loads_oob(header, buffers)


def _segment_layout(header: bytes, raws: List[memoryview]):
    """Compute (total_size, [(offset, part), ...]) for a segment.
    Parts are either bytes (metadata words) or the raw buffers."""
    parts: List[Tuple[int, Any]] = [
        (8, struct.pack("<Q", len(header))),
        (16, header),
    ]
    pos = 16 + len(header)
    for r in raws:
        pos = _align(pos)
        parts.append((pos, struct.pack("<Q", r.nbytes)))
        pos = _align(pos + 8)
        parts.append((pos, r))
        pos += r.nbytes
    total = _align(pos)
    parts.insert(0, (0, struct.pack("<Q", total)))
    return total, parts


def iter_segment_chunks(header: bytes, raws: List[memoryview],
                        chunk: int = 8 * 1024 * 1024):
    """Yield the byte stream of a segment (exactly the put_raw wire
    layout) in ~chunk-sized pieces without materializing the whole
    segment — the transport for shm-less clients streaming a large put
    to the hub (reference: util/client/server/dataservicer.py chunked
    PutObject). Returns (total_size, generator)."""
    total, parts = _segment_layout(header, raws)
    # every piece — padding included — funnels through the same
    # accumulate-and-flush loop, so acc never exceeds chunk regardless
    # of alignment gaps vs chunk size
    pieces: List[Any] = []
    pos = 0
    for off, part in parts:
        if off != pos:
            pieces.append(b"\x00" * (off - pos))
            pos = off
        mv = part if isinstance(part, memoryview) else memoryview(part)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        pieces.append(mv)
        pos += mv.nbytes
    if pos != total:
        pieces.append(b"\x00" * (total - pos))

    def gen():
        acc = bytearray()
        for p in pieces:
            mv = memoryview(p)
            i, n = 0, mv.nbytes
            while i < n:
                take = min(chunk - len(acc), n - i)
                acc += mv[i:i + take]
                i += take
                if len(acc) >= chunk:
                    yield bytes(acc)
                    acc = bytearray()
        if acc:
            yield bytes(acc)

    return total, gen()


class MappedSegment:
    """An open mmap of one object segment; kept alive while views exist.

    `writable` means THIS process created the segment (put_raw) and is
    therefore its sole writer — only such segments may be recycled into
    the warm pool on free() (a reader recycling a segment another
    process also pooled would double-assign the same pages).
    `size` is the logical object size; the mmap may be longer when the
    segment was carved from a recycled file.
    `faulted` means this mapping's pages have been WRITTEN THROUGH (its
    PTEs are populated): a put into it is a pure memcpy (~8 GiB/s here)
    instead of 64Ki soft faults + memcpy (~1.4 GiB/s). The cold path
    writes via os.write — the file's pages exist but this mapping never
    faulted them — so only pool-path/prewarmed segments qualify."""

    __slots__ = ("path", "mm", "size", "writable", "faulted")

    def __init__(self, path: str):
        self.path = path
        fd = os.open(path, os.O_RDWR)
        try:
            st = os.fstat(fd)
            self.mm = mmap.mmap(fd, st.st_size)
        finally:
            os.close(fd)
        _advise_hugepage(self.mm)
        self.size = st.st_size
        self.writable = False
        self.faulted = False

    @classmethod
    def from_fd(cls, path: str, fd: int, size: int) -> "MappedSegment":
        """Map the WRITER'S OWN fd (before close): re-opening by path
        could observe a concurrent rewriter's fresh, incomplete file
        (speculative task retry of the same object id)."""
        seg = cls.__new__(cls)
        seg.path = path
        seg.mm = mmap.mmap(fd, size)
        _advise_hugepage(seg.mm)
        seg.size = size
        seg.writable = True
        seg.faulted = False
        return seg


def _write_all(fd: int, data) -> None:
    """write() can return short (and caps at ~2 GiB per call) — loop."""
    view = memoryview(data)
    written = 0
    while written < view.nbytes:
        written += os.write(fd, view[written:])


class ShmObjectStore:
    """Per-process facade over the node's /dev/shm session directory."""

    def __init__(self, session_dir: str):
        self.dir = os.path.join(session_dir, "objects")
        os.makedirs(self.dir, exist_ok=True)
        self._segments: dict[str, MappedSegment] = {}
        self._lock = threading.Lock()
        # warm-pool of recycled writer segments: [(mmap_len, seg), ...]
        self._pool: List[Tuple[int, MappedSegment]] = []
        self._pool_bytes = 0

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def _pool_take(self, total: int) -> Optional[MappedSegment]:
        """Pop the smallest pooled segment whose mmap covers `total`,
        preferring already-faulted mappings: a recycled cold-path
        segment (file pages warm, mapping unfaulted) must not best-fit
        its way ahead of a prewarmed/pool-written one — the faulted
        mapping copies ~5x faster (see MappedSegment.faulted)."""
        with self._lock:
            best = -1
            for i, (cap, seg) in enumerate(self._pool):
                if cap < total:
                    continue
                if best < 0:
                    best = i
                    continue
                bcap, bseg = self._pool[best]
                if (seg.faulted, -cap) > (bseg.faulted, -bcap):
                    best = i
            if best < 0:
                return None
            cap, seg = self._pool.pop(best)
            self._pool_bytes -= cap
            return seg

    def prewarm(self, nbytes: int) -> None:
        """Fault `nbytes` of anonymous pooled segments through their
        mappings (the plasma trick: the arena is faulted once at
        startup, objects recycle its pages). Called from a background
        thread at driver init, so by the first large put the pool
        already holds warm pages and the put is a single memcpy. Split
        into two segments when the budget allows: carving an object
        from a much-larger segment truncates away its warm tail, so
        right-sized halves beat one big arena. A pool-cap overflow or
        any OS error just skips the optimization."""
        if nbytes <= 0:
            return
        if nbytes >= 128 * 1024 * 1024:
            half = nbytes // 2
            self._prewarm_one(half)
            self._prewarm_one(nbytes - half)
        else:
            self._prewarm_one(nbytes)

    def _prewarm_one(self, nbytes: int) -> None:
        name = f".pool.{uuid.uuid4().hex}"
        path = os.path.join(self.dir, name)
        try:
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.truncate(fd, nbytes)
                seg = MappedSegment.from_fd(path, fd, nbytes)
            finally:
                os.close(fd)
            # fault every page by writing through the mapping (writes —
            # not reads — populate the PTEs; a read maps the shared
            # zero page and the first real write still faults)
            mm = seg.mm
            step = 8 * 1024 * 1024
            zeros = bytes(step)
            for off in range(0, nbytes, step):
                end = min(off + step, nbytes)
                mm[off:end] = zeros[: end - off]
            seg.faulted = True
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        with self._lock:
            if (
                self._pool_bytes + nbytes <= _POOL_MAX_BYTES
                and len(self._pool) < _POOL_MAX_SEGMENTS
            ):
                self._pool.append((nbytes, seg))
                self._pool_bytes += nbytes
                return
        try:
            os.unlink(path)
        except OSError:
            pass

    def _layout(self, header: bytes, raws: List[memoryview]):
        return _segment_layout(header, raws)

    def put(self, name: str, obj: Any) -> int:
        """Serialize obj into a new segment. Returns segment size."""
        header, buffers = serialization.dumps_oob(obj)
        return self.put_raw(name, header, [b.raw() for b in buffers])

    def put_raw(self, name: str, header: bytes, raws: List[memoryview]) -> int:
        """Write a segment from pre-serialized (header, buffers).

        Recycled path: memcpy into an already-faulted pooled segment
        (np.copyto for large buffers — the single-core tmpfs ceiling,
        ~7 GiB/s here). Cold path: sequential os.write, NOT mmap
        assignment — writing through a fresh mmap faults one page at a
        time while write() bulk-copies in the kernel."""
        total, parts = self._layout(header, raws)
        path = self._path(name)
        # a retried task may rewrite the same object id; the old segment
        # stays valid for existing mmaps after the unlink
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        seg = self._pool_take(total)
        if seg is not None:
            # grow-only: the self-terminating layout lets readers
            # ignore a slack tail, so carving a smaller object from a
            # larger recycled file never truncates (truncating would
            # free exactly the warm tail pages the pool exists to keep)
            if os.path.getsize(seg.path) < total:
                os.truncate(seg.path, total)
            mm = seg.mm
            for off, part in parts:
                if isinstance(part, memoryview) and part.nbytes >= (1 << 16):
                    import numpy as np

                    src = part if part.format == "B" and part.ndim == 1 \
                        else part.cast("B")
                    np.copyto(
                        np.frombuffer(mm, np.uint8, src.nbytes, off),
                        np.frombuffer(src, np.uint8),
                    )
                else:
                    n = part.nbytes if isinstance(part, memoryview) else len(part)
                    mm[off:off + n] = bytes(part)
            os.rename(seg.path, path)
            seg.path = path
            seg.size = total
            # the copy above wrote the object's span through the mmap;
            # for recycled cold-path segments this is what faults them
            seg.faulted = True
        else:
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                pending: List[bytes] = []
                pos = 0
                for off, part in parts:
                    if off != pos:
                        pending.append(b"\x00" * (off - pos))
                        pos = off
                    if isinstance(part, memoryview):
                        # flush small parts, then bulk-write the buffer
                        if pending:
                            _write_all(fd, b"".join(pending))
                            pending = []
                        _write_all(
                            fd,
                            part.cast("B")
                            if part.format != "B" or part.ndim != 1
                            else part,
                        )
                    else:
                        pending.append(part)
                    pos += part.nbytes if isinstance(part, memoryview) else len(part)
                if pos != total:
                    pending.append(b"\x00" * (total - pos))
                if pending:
                    _write_all(fd, b"".join(pending))
                seg = MappedSegment.from_fd(path, fd, total)
            finally:
                os.close(fd)
        with self._lock:
            self._segments[name] = seg
        return total

    def get(self, name: str) -> Any:
        """Map the segment and deserialize zero-copy (buffers view the mmap)."""
        with self._lock:
            seg = self._segments.get(name)
            if seg is None:
                seg = MappedSegment(self._path(name))
                self._segments[name] = seg
        header, buffers = _parse_segment(memoryview(seg.mm), seg.size)
        return serialization.loads_oob(header, buffers)

    def write_segment(self, name: str, data: bytes) -> None:
        """Install a segment fetched from another node (byte-identical
        copy of the producer's file; get() then maps it locally). The
        tmp name is per-process: concurrent fetchers of the same object
        must not race each other's os.replace."""
        path = self._path(name)
        tmp = f"{path}.fetch.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def contains(self, name: str) -> bool:
        return name in self._segments or os.path.exists(self._path(name))

    def drop_mapping(self, name: str) -> None:
        """Forget a READER mapping of a freed object. Writer segments
        keep their free()/pool recycle path untouched; reader mappings
        of remote or sibling-process segments have no pool value, and
        sustained serving (one mapped payload segment per request)
        would otherwise grow the table by one dead entry per request.
        The mmap pages stay alive while fetched views reference them —
        the buffer protocol keeps the exporting mmap pinned."""
        with self._lock:
            seg = self._segments.get(name)
            if seg is not None and not seg.writable:
                del self._segments[name]

    def free(self, name: str) -> None:
        with self._lock:
            seg = self._segments.pop(name, None)
            if seg is not None and seg.writable:
                cap = len(seg.mm)
                # advisory pre-check: skip the rename+unlink round-trip
                # when the pool is already full. Going stale here only
                # forgoes a recycle — the authoritative check before
                # insert below is what enforces the caps.
                no_room = (
                    self._pool_bytes + cap > _POOL_MAX_BYTES
                    or len(self._pool) >= _POOL_MAX_SEGMENTS
                )
        if seg is not None and seg.writable:
            # Recycle the warm pages under an anonymous name. Free means
            # "no live borrowers" (same contract as the reference's
            # ray._private.internal_api.free — objects are deleted even
            # if still referenced); a racing unlink by the hub just
            # defeats the recycle. Rename FIRST, then check pool room
            # and insert under ONE lock acquisition: checking under a
            # separate acquisition let two concurrent frees both pass
            # the byte-cap test and blow past _POOL_MAX_BYTES.
            if no_room:
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
                return
            pooled = os.path.join(self.dir, f".pool.{uuid.uuid4().hex}")
            try:
                os.rename(seg.path, pooled)
            except OSError:
                return  # hub already unlinked it; drop the segment
            seg.path = pooled
            with self._lock:
                if (
                    self._pool_bytes + cap <= _POOL_MAX_BYTES
                    and len(self._pool) < _POOL_MAX_SEGMENTS
                ):
                    self._pool.append((cap, seg))
                    self._pool_bytes += cap
                    return
            # pool is full after all: drop the renamed file (the mmap
            # stays valid for any live views)
            try:
                os.unlink(pooled)
            except OSError:
                pass
            return
        # The mmap stays valid for existing views even after unlink.
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass

    def estimate_size(self, obj: Any) -> int:
        """Cheap size probe used to pick inline vs shm path."""
        try:
            import numpy as np

            if isinstance(obj, np.ndarray):
                return obj.nbytes
        except Exception:
            pass
        return -1  # unknown; caller serializes and checks
