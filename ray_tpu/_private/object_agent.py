"""Per-node object-plane endpoint: out-of-band bulk object transfer.

The hub reactor is the control plane; routing multi-GB segment bytes
through it serializes every transfer behind one thread and every other
message behind the transfer (the exact failure mode "The Big Send-off"
describes for control-plane collectives). This agent is the data plane:
one listener per node, owned by the hub process on the head node and by
node_agent.py on remote hosts, serving two verbs over the PR 2 wire
codec (serialization.dumps_frame / loads_frame):

  ("obj_get", {name, fallback_spill_dir?})
      -> ("obj_data", {data, total, last})  * k   (8 MiB chunks)
      -> ("obj_error", {error})                   (missing/unreadable)

  ("obj_put", {name, data, last})  * k
      -> ("obj_put_ok", {size}) | ("obj_error", {error})
      Chunks append into a connection-private tmp file that is
      os.replace'd into the objects dir on the last chunk, so readers
      never observe a partial segment and a failed stream leaves
      nothing behind.

Consumers resolve the endpoint once through the hub's ownership
directory (protocol.RESOLVE_OBJECT) and cache it; any transfer error
falls back to the hub-relay path (FETCH_OBJECT / PUT_CHUNK), so the
agent can die mid-stream without losing data — only bandwidth.

Reference analogue: src/ray/object_manager/object_manager.h (push/pull
between plasma stores over its own RPC service, never through the GCS).

Chaos hook: a ``close_after:N`` directive in the RAY_TPU_CHAOS_PLAN
(legacy alias: RAY_TPU_CHAOS_OBJECT_AGENT="close_after:N") closes every
connection after serving N data chunks — the tier-1 harness for
"serving peer dies mid-transfer" (tests/test_object_plane.py). The
agent hosts the "object_agent" scope of the chaos engine (chaos.py).
"""

from __future__ import annotations

import os
import threading
from multiprocessing.connection import Listener
from typing import Optional, Tuple

from . import protocol as P
from .debug import log_exc
from .serialization import dumps_frame, loads_frame

CHUNK = 8 * 1024 * 1024


def pull_segment_bytes(endpoint: str, name: str) -> bytes:
    """One-shot direct pull of a whole segment into memory.

    The lightweight consumer path for serve response payloads
    (serve/_private/payloads.py): a proxy/handle reading a one-shot
    response body has no use for the full CoreClient fetch dance —
    store install, REPLICA_ADDED registration, resolve caching,
    connection pooling — so this helper opens ONE connection, streams
    the segment, and returns the assembled bytes (decode with
    object_store.decode_segment_bytes). Raises on ANY irregularity;
    callers fall back to the full client fetch path, which ends in the
    hub relay.
    """
    from .client import connect_hub

    conn = connect_hub(endpoint)
    try:
        conn.send_bytes(dumps_frame((P.OBJ_GET, {"name": name})))
        out = bytearray()
        total = None
        while True:
            msg_type, p = loads_frame(conn.recv_bytes())
            if msg_type == P.OBJ_ERROR:
                raise OSError(p.get("error") or "agent fetch failed")
            if msg_type != P.OBJ_DATA:
                raise OSError(f"unexpected frame {msg_type}")
            out += p["data"]
            total = p.get("total", total)
            if p.get("last"):
                break
        if total is not None and len(out) != total:
            raise OSError(
                f"short object-agent stream: {len(out)}/{total} bytes"
            )
        return bytes(out)
    finally:
        try:
            conn.close()
        except Exception:
            pass


class ObjectAgent:
    """Serve shm-segment reads/writes for one node's object directory.

    Thread-per-connection blocking IO: transfers are few and long, the
    per-chunk work is kernel bulk copies that release the GIL, and a
    slow peer then stalls only its own thread — a property no control-
    plane reactor (the single hub loop, or a reactor shard in the
    RAY_TPU_HUB_SHARDS>1 topology, hub_shards.py) should offer: bulk
    bytes on a reactor thread would park every peer's dispatch behind a
    memcpy.
    """

    def __init__(self, objects_dir: str, spill_dir: str = "",
                 host: str = "127.0.0.1", port: int = 0,
                 unix_path: Optional[str] = None):
        self.objects_dir = objects_dir
        self.spill_dir = spill_dir
        if unix_path is not None:
            self.listener = Listener(unix_path, family="AF_UNIX")
            self.endpoint = unix_path
        else:
            self.listener = Listener((host, port), family="AF_INET")
            lhost, lport = self.listener.address
            self.endpoint = f"tcp://{lhost}:{lport}"
        # transfer counters, sampled by the owner's heartbeat into the
        # ray_tpu_object_direct_* builtin metrics. Plain ints mutated
        # under _stats_lock: serving threads increment, the hub/agent
        # heartbeat thread reads.
        self._stats_lock = threading.Lock()
        self.bytes_served = 0
        self.bytes_received = 0
        self.transfers = 0
        from . import chaos as _chaos_mod

        eng = _chaos_mod.engine_for("object_agent")
        self._chaos = eng
        self._chaos_close_after = eng.close_after if eng is not None else 0
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="object-agent-accept"
        )
        self._accept_thread.start()

    # ------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn = self.listener.accept()
            except OSError:
                return  # listener closed
            except Exception:
                if self._closed:
                    return
                log_exc("object agent accept error")
                continue
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="object-agent-conn",
            ).start()

    def _path(self, name: str) -> Optional[str]:
        """Resolve a segment name inside the objects/spill dirs only —
        a peer-supplied name must not escape them."""
        if not name or os.sep in name or name.startswith("."):
            return None
        path = os.path.join(self.objects_dir, name)
        if os.path.exists(path):
            return path
        if self.spill_dir:
            spilled = os.path.join(self.spill_dir, name)
            if os.path.exists(spilled):
                return spilled
        return path  # open() will raise; caller reports obj_error

    def _serve_conn(self, conn) -> None:
        chunks_left = self._chaos_close_after or -1
        put_state: Optional[Tuple[str, str, object]] = None  # (name, tmp, file)
        try:
            while True:
                msg_type, p = loads_frame(conn.recv_bytes())
                if msg_type == P.OBJ_GET:
                    chunks_left = self._serve_get(conn, p, chunks_left)
                    if chunks_left == 0:
                        self._chaos.record("close_after")
                        return  # chaos: simulated mid-stream death
                elif msg_type == P.OBJ_PUT:
                    put_state = self._serve_put(conn, p, put_state)
                    if chunks_left > 0:
                        chunks_left -= 1
                        if chunks_left == 0:
                            self._chaos.record("close_after")
                            return  # chaos: simulated mid-stream death
                else:
                    conn.send_bytes(dumps_frame(
                        (P.OBJ_ERROR, {"error": f"unknown verb {msg_type}"})
                    ))
        except (EOFError, OSError, ValueError):
            pass  # peer gone / torn frame: drop the connection
        except Exception:
            log_exc("object agent connection error")
        finally:
            if put_state is not None:
                # incomplete inbound stream: drop the partial tmp file
                try:
                    put_state[2].close()
                    os.unlink(put_state[1])
                except OSError:
                    pass
            try:
                conn.close()
            except Exception:
                pass

    def _serve_get(self, conn, p, chunks_left: int) -> int:
        path = self._path(p.get("name", ""))
        try:
            f = open(path, "rb") if path else None
            if f is None:
                raise OSError("bad segment name")
        except OSError as err:
            conn.send_bytes(dumps_frame((P.OBJ_ERROR, {"error": str(err)})))
            return chunks_left
        with f:
            total = os.fstat(f.fileno()).st_size
            sent = 0
            while True:
                data = f.read(CHUNK)
                sent += len(data)
                last = sent >= total
                conn.send_bytes(dumps_frame(
                    (P.OBJ_DATA, {"data": data, "total": total, "last": last})
                ))
                if chunks_left > 0:
                    chunks_left -= 1
                    if chunks_left == 0:
                        return 0  # chaos trip: caller closes the conn
                if last:
                    break
        with self._stats_lock:
            self.bytes_served += total
            self.transfers += 1
        return chunks_left

    def _serve_put(self, conn, p, put_state):
        name = p.get("name", "")
        safe = name and os.sep not in name and not name.startswith(".")
        if put_state is None:
            if not safe:
                conn.send_bytes(dumps_frame(
                    (P.OBJ_ERROR, {"error": f"bad segment name {name!r}"})
                ))
                return None
            os.makedirs(self.objects_dir, exist_ok=True)
            tmp = os.path.join(
                self.objects_dir, f".direct.{threading.get_ident():x}.{name}"
            )
            put_state = (name, tmp, open(tmp, "wb"))
        elif put_state[0] != name:
            conn.send_bytes(dumps_frame(
                (P.OBJ_ERROR, {"error": "interleaved puts on one connection"})
            ))
            return put_state
        put_state[2].write(p["data"])
        if p.get("last"):
            name, tmp, f = put_state
            size = f.tell()
            f.close()
            os.replace(tmp, os.path.join(self.objects_dir, name))
            with self._stats_lock:
                self.bytes_received += size
                self.transfers += 1
            conn.send_bytes(dumps_frame((P.OBJ_PUT_OK, {"size": size})))
            return None
        return put_state

    # ------------------------------------------------------------ lifecycle
    def stats(self) -> dict:
        with self._stats_lock:
            return {
                "bytes_served": self.bytes_served,
                "bytes_received": self.bytes_received,
                "transfers": self.transfers,
            }

    def close(self) -> None:
        self._closed = True
        try:
            self.listener.close()
        except Exception:
            pass
