"""Control hub: node registry, object directory, scheduler, actor manager.

This one component plays the roles the reference splits across three
processes — the GCS server (reference: src/ray/gcs/gcs_server/
gcs_server.h:90), the per-node raylet (src/ray/raylet/node_manager.h:122)
and its ClusterTaskManager/LocalTaskManager (src/ray/raylet/scheduling/),
and the plasma metadata plane. On a TPU host the control plane does not
need to be distributed the way Ray's is (scheduling decisions are
node-local; cross-host coordination happens through jax.distributed and
the collective layer), so an event-loop hub gives us the same semantics
with none of the cross-process consistency machinery.

Threading model: ONE state-plane thread owns all state (no locks); it
multiplexes timeouts through a deadline heap. Connection I/O has two
shapes, selected by RAY_TPU_HUB_SHARDS (config "hub_shards", default
min(4, cpu count)):

  - shards == 1: the state-plane thread IS the reactor — it owns every
    socket too, the same single-reactor shape as the raylet's
    instrumented asio loop (reference: src/ray/common/asio/
    instrumented_io_context.h). This path is byte-for-byte the pre-shard
    behavior.
  - shards > 1: N reactor-shard threads own the sockets + wire codec
    (hub_shards.py) and reach the scheduler / object-directory state
    services over SPSC message rings — the GCS/raylet split re-done
    natively in one process. State stays single-threaded either way.

Scheduling: resource-based admission (CPU/TPU/custom resources +
placement-group bundle accounting) then dispatch to an idle worker from
the pool, spawning new workers on demand up to a cap — mirroring the
reference's lease-based WorkerPool flow (src/ray/raylet/worker_pool.h,
local_task_manager.cc:124 DispatchScheduledTasksToWorkers) without the
lease round-trip: the hub pushes tasks straight to workers.

Fault tolerance: worker death is detected by connection EOF (the raylet
uses SIGCHLD, reference: src/ray/raylet/worker_pool.cc); running tasks
are retried per max_retries, actors restarted per max_restarts
(reference: src/ray/gcs/gcs_server/gcs_actor_manager.h:96,569).
"""

from __future__ import annotations

import heapq
import itertools
import os
import selectors
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from multiprocessing.connection import Listener
from typing import Any, Dict, List, Optional, Set, Tuple

from . import protocol as P
from .debug import log_exc, proc_rss_bytes
from .fairsched import FairScheduler, QuotaInfeasibleError
from .hub_shards import ShardStats as _ShardStats
from .ids import WorkerID
from .serialization import (
    dumps_frame,
    dumps_inline,
    loads_frame,
    loads_inline,
)

# Fault injection (reference: src/ray/rpc/rpc_chaos.h env-selected
# per-method failure, grown into a seeded deterministic plan): the hub
# hosts the "hub" scope of the chaos engine — message drop/delay/dup at
# the dispatch seam, timed conn/worker faults, node partitions. See
# chaos.py for the RAY_TPU_CHAOS_PLAN grammar; with no plan the engine
# is None and every injection point is one attribute load.
from . import chaos as _chaos_mod


@dataclass
class ObjEntry:
    ready: bool = False
    kind: str = ""
    payload: Any = None
    size: int = 0
    node_id: str = "node0"  # producer node (VAL_SHM segments live there)
    spilled: bool = False  # primary copy moved to disk (LRU eviction)
    # ownership/location directory (reference: the ownership table +
    # object directory, src/ray/core_worker/reference_count.h +
    # object_manager/ownership_object_directory.h): nodes holding a
    # byte-identical copy installed by a direct fetch. The owner
    # (node_id) is implicit; replicas let RESOLVE_OBJECT fail over when
    # the owner dies. None until the first replica (the common case
    # allocates nothing).
    replicas: Optional[Set[str]] = None
    # (conn, req_id) waiters registered by pending GETs
    task_waiters: List[bytes] = field(default_factory=list)  # task_ids blocked on this obj
    # dependency pins: in-flight tasks (and live actors, for creation
    # args) holding this object alive against ownership-GC release.
    # Mirrors the reference's "submitted task references"
    # (src/ray/core_worker/reference_count.h) without per-borrower
    # bookkeeping: the hub sees every submit, so it counts directly.
    pins: int = 0
    release_pending: bool = False  # owner released while pinned
    # leak attribution (`ray_tpu memory`): the process holding the
    # ObjectRef — the submitter for task returns, the putter for puts
    # ("driver" / "client-N" / a worker id; "" = placeholder entry).
    # created_t is the entry's birth (monotonic), so age is a duration
    # per GL008; display code converts to seconds-old at list time.
    owner: str = ""
    created_t: float = field(default_factory=time.monotonic)


@dataclass
class NodeEntry:
    """One host in the cluster. The head host ("node0") is managed by
    the hub itself (workers are direct subprocesses); remote hosts are
    managed by a node agent (node_agent.py) reached over TCP — the
    reference's raylet registering with the GCS
    (src/ray/gcs/gcs_server/gcs_node_manager.h)."""

    node_id: str
    hostname: str
    ip: str
    session_dir: str
    total: Dict[str, float]
    avail: Dict[str, float]
    free_tpu_chips: Set[int] = field(default_factory=set)
    # ICI topology: chip id -> mesh coordinate (empty = unknown); the
    # SLICE strategy reserves coordinate-contiguous chips from it
    chip_coords: Dict[int, tuple] = field(default_factory=dict)
    # chips reserved by ready SLICE placement groups: out of the free
    # pool, placeable only via their PG bundle
    pg_reserved_chips: Set[int] = field(default_factory=set)
    max_workers: int = 4
    agent_conn: Any = None  # None => head node (hub-local spawning)
    alive: bool = True
    spawning: int = 0
    # how many of the in-flight spawns were requested FOR ACTOR wants —
    # pooled-task spawns must not eat the actor quota for a round
    spawning_actor: int = 0
    # shm object-store budget (reference: plasma eviction_policy.h LRU +
    # external_storage.py spilling): bytes of live segments vs the cap
    store_cap: float = 0.0  # 0 = unlimited
    store_used: float = 0.0
    # out-of-band object plane: this node's object_agent endpoint
    # ("tcp://host:port" or an AF_UNIX path; "" = agent disabled —
    # transfers to/from this node ride the hub relay)
    object_endpoint: str = ""
    # monotonic stamp of the last agent heartbeat; the heartbeat-miss
    # watchdog declares the node dead past the configured threshold
    # (reference: gcs_node_manager heartbeat timeout). 0 = head node /
    # never heartbeated.
    last_heartbeat_t: float = 0.0


@dataclass
class TaskSpec:
    task_id: bytes
    fn_id: str
    args_kind: str
    args_payload: Any
    return_ids: List[bytes]
    resources: Dict[str, float]
    options: dict
    deps_remaining: int = 0
    retries_left: int = 0
    is_actor_create: bool = False
    actor_id: Optional[bytes] = None  # for actor tasks
    method: Optional[str] = None
    ready_id: Optional[bytes] = None  # actor creation ready object
    # arg object ids pinned for this task's lifetime (cleared on unpin
    # so finalization paths can safely run more than once)
    pinned_deps: List[bytes] = field(default_factory=list)
    # distributed tracing: (trace_id, client_submit_span_id) when the
    # submit was head-sampled (util/tracing.py). None = untraced — every
    # span-emission site gates on it, so the default path adds nothing.
    trace: Optional[tuple] = None
    # submitting process's label (_conn_label) — flows onto the task's
    # return objects as their owner for `ray_tpu memory` attribution
    owner: str = ""
    # submitted through the bulk SUBMIT_TASKS frame (RemoteFunction.map):
    # the caller declared a homogeneous throughput-oriented fan-out, so
    # the scheduler may pipeline it behind busy workers. Individually
    # submitted tasks keep strict work-stealing placement (lowest
    # latency to first execution) and never pipeline.
    bulk: bool = False


@dataclass
class WorkerEntry:
    worker_id: str
    conn: Any = None
    proc: Any = None
    # the worker's own os.getpid(), reported in its HELLO — the only
    # pid the head has for agent-spawned workers (proc lives on the
    # remote node agent, so proc.pid is unavailable here)
    pid: Optional[int] = None
    node_id: str = "node0"
    runtime_env_hash: str = ""  # workers only serve matching runtime envs
    spawned_for_actor: bool = False  # purpose of the spawn (quota math)
    # gang preemption in progress: this worker is being killed to free
    # its gang's reservation; its task requeues / its actor restarts
    # WITHOUT burning the retry/restart budget
    preempted: bool = False
    state: str = "starting"  # starting | idle | busy | actor | dead
    # dispatch pipeline: FIFO of tasks assigned to this worker. The head
    # is executing; followers sit in the worker process's own task queue
    # (it drains sequentially), so TASK_DONE/EXEC frames coalesce instead
    # of paying a wake+syscall round-trip per task. Plain tasks only —
    # see _find_pipeline_worker for the eligibility gate.
    assigned: deque = field(default_factory=deque)
    pipe_ok: bool = False  # every task in `assigned` is pipeline-eligible
    actor_id: Optional[bytes] = None
    seen_fns: Set[str] = field(default_factory=set)
    tpu_chips: Tuple[int, ...] = ()  # chips assigned to the current task
    # jax binds devices at first import, so once a worker has run a TPU task
    # its chips are pinned for the worker's lifetime; the scheduler only
    # reuses it for tasks wanting the same chip count (chip affinity).
    pinned_chips: Optional[Tuple[int, ...]] = None
    # tracing: monotonic spawn-request/HELLO stamps; the first traced
    # task dispatched onto a freshly spawned worker attributes the
    # spawn window to its trace as a "spawn" stage span (once)
    spawned_t: float = 0.0
    connected_t: float = 0.0
    spawn_span_done: bool = False
    # dispatch generation: bumped by every _send_exec so a per-task
    # timeout timer armed for attempt N can never kill attempt N+1 of
    # the SAME (retried, hence identical) TaskSpec on this worker
    exec_gen: int = 0

    # `current_task` predates the pipeline: it is now a view of the
    # assigned queue's head. The setter keeps the single-assignment
    # call sites working — assigning replaces the whole queue. (Not an
    # annotated attribute, so the dataclass machinery ignores it.)
    @property
    def current_task(self) -> Optional[TaskSpec]:
        return self.assigned[0] if self.assigned else None

    @current_task.setter
    def current_task(self, spec: Optional[TaskSpec]) -> None:
        self.assigned.clear()
        if spec is not None:
            self.assigned.append(spec)


@dataclass
class ActorEntry:
    actor_id: bytes
    fn_id: str
    args_kind: str
    args_payload: Any
    resources: Dict[str, float]
    options: dict
    ready_id: bytes
    state: str = "pending"  # pending | alive | restarting | dead
    worker_id: Optional[str] = None
    name: str = ""
    restarts_left: int = 0
    pending_calls: deque = field(default_factory=deque)
    inflight: Dict[bytes, TaskSpec] = field(default_factory=dict)  # task_id -> spec
    pool: Optional[tuple] = None  # resource pool holding the actor's lifetime resources
    # creation-arg object pins, held for the actor's lifetime so a
    # restart can replay the creation args; released when the actor is
    # permanently dead
    creation_pins: List[bytes] = field(default_factory=list)


@dataclass
class PGEntry:
    pg_id: bytes
    bundles: List[Dict[str, float]]
    strategy: str
    name: str = ""
    ready: bool = True
    # multi-tenant scheduling identity (fairsched): the creating job's
    # tenant/priority decide who may preempt whom
    tenant: str = "default"
    priority: int = 0
    job_id: str = ""
    seq: int = 0  # creation order (newest-first victim selection)
    # set on a preempted PG: stand aside from re-reserving until the
    # beneficiary reservation (pg_id) is ready or gone, so the victim
    # cannot re-grab the chips it was just preempted off of. The
    # monotonic deadline bounds the stand-aside: a beneficiary that
    # never seats (mis-estimated feasibility) must not starve its
    # victims forever.
    yield_to: Optional[bytes] = None
    yield_until: float = 0.0
    # last time THIS entry ATTEMPTED preemption (monotonic): the 50ms
    # pg_ready poll must not turn a stuck reservation into a kill storm
    last_preempt_t: float = 0.0
    # rounds of victims this entry has shed without seating: capped so
    # a misestimated reservation cannot kill/restart the same gangs
    # every backoff window forever
    preempt_rounds: int = 0
    # per-bundle available resources (bundle reservations are exclusive)
    bundle_avail: List[Dict[str, float]] = field(default_factory=list)
    # node each bundle was reserved on (set when ready)
    bundle_nodes: List[str] = field(default_factory=list)
    # SLICE only: the specific ICI-contiguous chip ids reserved per
    # bundle; tasks scheduled into bundle i run on exactly these chips
    bundle_chips: List[tuple] = field(default_factory=list)


@dataclass
class StreamEntry:
    """State of one streaming-generator task (reference:
    core_worker streaming generator + ObjectRefGenerator _raylet.pyx:280):
    yielded object ids in order, consumer cursor for backpressure, and
    waiters blocked on indices not yet produced."""

    oids: List[bytes] = field(default_factory=list)
    ended: bool = False
    consumed: int = 0
    next_waiters: Dict[int, List[Tuple[Any, int]]] = field(default_factory=dict)
    credit_waiters: List[Tuple[int, Any, int]] = field(default_factory=list)


@dataclass
class GetReq:
    conn: Any
    req_id: int
    remaining: Set[bytes]
    all_ids: List[bytes]
    deadline: Optional[float] = None
    done: bool = False


@dataclass
class WaitReq:
    conn: Any
    req_id: int
    ids: List[bytes]
    num_returns: int
    deadline: Optional[float] = None
    done: bool = False
    # incremental ready counter: arrivals bump this instead of re-scanning
    # all ids (a 1k-ref wait used to cost O(n) per arrival = O(n^2) total)
    n_ready: int = 0


def _sum_bundle_resources(bundles: List[Dict[str, float]]) -> Dict[str, float]:
    """Fold a PG's bundles into one total-resource dict."""
    total: Dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            total[k] = total.get(k, 0.0) + v
    return total


def _find_chip_path(coords: Dict[int, tuple], free: Set[int],
                    length: int) -> Optional[List[int]]:
    """A simple path of `length` chips through the free subset of the
    ICI mesh (neighbors differ by 1 in exactly one coordinate — v5e 2D
    meshes don't wrap below pod scale). Splitting such a path into
    consecutive chunks yields per-bundle chip sets that are each
    ICI-connected, which is what SLICE promises.

    Bounded DFS with deterministic seed order (lexicographic coords) —
    exact for the single-host sizes this runs on (<=8 chips per host on
    v5e; a few hundred at most), bailing out after a fixed step budget
    so a fragmented big mesh can't stall the hub reactor.
    """
    usable = [c for c in free if c in coords]
    if length <= 0 or len(usable) < length:
        return None
    if length == 1:
        return [min(usable, key=lambda c: coords[c])]
    by_coord = {coords[c]: c for c in usable}

    def neighbors(c: int):
        base = coords[c]
        for dim in range(len(base)):
            for d in (-1, 1):
                nb = list(base)
                nb[dim] += d
                n = by_coord.get(tuple(nb))
                if n is not None:
                    yield n

    budget = 50_000
    for seed in sorted(usable, key=lambda c: coords[c]):
        stack = [(seed, (seed,))]
        while stack and budget > 0:
            budget -= 1
            node, path = stack.pop()
            if len(path) == length:
                return list(path)
            for n in neighbors(node):
                if n not in path:
                    stack.append((n, path + (n,)))
        if budget <= 0:
            break
    return None


class Hub:
    def __init__(
        self,
        session_dir: str,
        resources: Dict[str, float],
        max_workers: Optional[int] = None,
        tpu_chip_ids: Optional[List[int]] = None,
        tpu_chip_coords: Optional[Dict[int, tuple]] = None,
        worker_env: Optional[Dict[str, str]] = None,
        tcp: bool = False,
        host: str = "127.0.0.1",
        port: int = 0,
        object_store_memory: Optional[float] = None,
        kv_store_path: Optional[str] = None,
    ):
        import socket as _socket
        import tempfile as _tempfile

        if object_store_memory is None:
            object_store_memory = float(
                os.environ.get("RAY_TPU_OBJECT_STORE_MEMORY", 0)
            )
        self.spill_dir = os.environ.get("RAY_TPU_SPILL_DIR") or os.path.join(
            _tempfile.gettempdir(), "ray_tpu_spill_" + os.path.basename(session_dir)
        )

        # config table + chaos are re-read per hub so tests can set env
        # after first import (reference: ray_config_def.h + rpc_chaos.h)
        from . import config as _config_mod

        _config_mod.reload()
        self.config = _config_mod.RAY_TPU_CONFIG
        # None (no plan / nothing for the hub scope) = inert fault
        # plane: _handle/_handle_sharded pay one attribute load
        self._chaos = _chaos_mod.engine_for("hub")
        self.session_dir = session_dir
        os.makedirs(session_dir, exist_ok=True)
        if tcp:
            # Cluster mode: node agents and their workers dial in over
            # TCP (the AF_UNIX hub cannot leave the host — VERDICT r1).
            self.listener = Listener((host, port), family="AF_INET")
            lhost, lport = self.listener.address
            self.addr = f"tcp://{lhost}:{lport}"
        else:
            self.addr = os.path.join(session_dir, "hub.sock")
            self.listener = Listener(self.addr, family="AF_UNIX")
        self.max_workers = max_workers or max(4, int(resources.get("CPU", 4)))
        self.worker_env = dict(worker_env or {})
        head = NodeEntry(
            node_id="node0",
            hostname=_socket.gethostname(),
            ip=host,
            session_dir=session_dir,
            total=dict(resources),
            avail=dict(resources),
            free_tpu_chips=set(tpu_chip_ids or []),
            chip_coords=dict(tpu_chip_coords or {}),
            max_workers=self.max_workers,
            agent_conn=None,
            store_cap=object_store_memory,
        )
        self.nodes: Dict[str, NodeEntry] = {"node0": head}
        self.agent_conns: Dict[Any, str] = {}  # agent conn -> node_id
        # per-node LRU of live shm segments (oid -> size), oldest first
        from collections import OrderedDict as _OD

        self._lru: Dict[str, "_OD[bytes, int]"] = {"node0": _OD()}

        self.objects: Dict[bytes, ObjEntry] = {}
        self.functions: Dict[str, bytes] = {}
        self.tasks: Dict[bytes, TaskSpec] = {}  # pending+runnable normal tasks
        # Runnable tasks are queued per scheduling class (resource shape ×
        # placement pool), the reference's SchedulingKey idea (src/ray/
        # core_worker/transport/normal_task_submitter.h:45-58): placement is
        # tried only at each class's head, so a blocked class never costs a
        # scan and heterogeneous classes never block each other.
        self.runnable: Dict[tuple, deque] = {}
        self.workers: Dict[str, WorkerEntry] = {}
        self.conn_to_worker: Dict[Any, str] = {}
        # driver/client conns in HELLO order (value = (arrival seq,
        # monotonic HELLO stamp)): deterministic victim ordering for
        # chaos conn_kill, pruned on disconnect. The driver conn is
        # never a victim (killing it is session teardown by design —
        # driver fate-sharing), and neither is a conn younger than the
        # grace period below (a kill landing between a client's HELLO
        # and its first request reply tests the race, not recovery).
        self.client_conns: Dict[Any, tuple] = {}
        self._client_conn_seq = itertools.count()
        # dispatch generation counter for per-task execute timeouts
        self._exec_seq = itertools.count(1)
        self.actors: Dict[bytes, ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}
        # permanently-dead actor ids, FIFO: beyond the cap the oldest
        # tombstones leave the actor tables (GL009: handler-grown
        # registries need a pruning edge; the reference likewise caps
        # its dead-actor cache, gcs_actor_manager maxDestroyedActors)
        self._dead_actors: deque = deque()
        self.pgs: Dict[bytes, PGEntry] = {}
        # multi-tenant scheduling policy: priority + fair-share
        # ordering, quota admission, gang preemption (fairsched.py).
        # Inert (O(1) no-ops) until the first job/tenant registers.
        self.fairsched = FairScheduler()
        self._tenant_gauges: Dict[str, dict] = {}
        # durable KV backend (reference: GCS StorageType in-memory vs
        # redis — gcs_server.h; here an append-log + snapshot on the
        # head's disk, _private/store.py). None = in-memory only.
        from .store import open_store

        # explicit argument wins over the machine-wide env default, and
        # the store takes an exclusive flock so two hubs can't interleave
        # appends into one log
        self._kv_store = open_store(
            kv_store_path or os.environ.get("RAY_TPU_KV_STORE_PATH"),
            fsync=os.environ.get("RAY_TPU_KV_STORE_FSYNC", "")
            in ("1", "true", "yes"),
        )
        self.kv: Dict[bytes, bytes] = (
            self._kv_store.load() if self._kv_store else {}
        )
        self.get_reqs: List[GetReq] = []
        self.obj_get_waiters: Dict[bytes, List[GetReq]] = {}
        self.obj_wait_waiters: Dict[bytes, List[WaitReq]] = {}
        # readiness-push subscriptions (SUBSCRIBE_READY/READY_PUSH):
        # oid -> conns to push to when it becomes ready, plus the
        # reverse index for O(subscribed) disconnect pruning. Entries
        # leave on push, free, and disconnect.
        self._ready_watchers: Dict[bytes, List[Any]] = {}
        self._ready_watch_conns: Dict[int, Set[bytes]] = {}
        # retransmit dedup: clients resend slow GET/WAIT requests every
        # ~2s (lost-reply tolerance); while the original is still parked
        # here, the resend must NOT register a second full waiter set.
        # Keyed by (id(conn), req_id); purged on reply and on disconnect.
        self._inflight_reqs: Dict[Tuple[int, int], Any] = {}
        self.dep_waiters: Dict[bytes, List[TaskSpec]] = {}
        self.timers: List[Tuple[float, int, Any]] = []  # (deadline, seq, callback)
        self._timer_seq = itertools.count()
        self._fetch_seq = itertools.count()
        # fid -> (conn, request payload, node_id); the payload keeps its
        # req_id/offset/length so a node-death replay preserves chunk
        # identity
        self._pending_fetches: Dict[int, Tuple[Any, dict, str]] = {}
        # in-progress chunked client puts: (conn id, name) -> open file
        self._client_puts: Dict[Tuple[int, str], Any] = {}
        self._spawn_wants: Dict[str, int] = {}
        self.streams: Dict[bytes, StreamEntry] = {}
        self.subscribers: Dict[str, List[Any]] = {}  # channel -> conns
        # lineage: producer TaskSpec per shm object, for reconstruction
        # after node loss (reference: task_manager.h lineage pinning +
        # object_recovery_manager.h:43 re-executing the producing task)
        self._lineage: Dict[bytes, TaskSpec] = {}
        self._lineage_order: deque = deque()
        # ownership GC: refs released before their producing task
        # finished — freed the moment the value arrives. Insertion-
        # ordered dict so the (rare) entries for ids that never
        # materialize can be evicted oldest-first.
        self._released_early: Dict[bytes, bool] = {}
        self._reconstruct_waiters: Dict[bytes, List[Tuple[Any, dict]]] = {}
        self._reconstructing: Set[bytes] = set()
        self._ended_streams: deque = deque()  # consumed stream ids, FIFO
        # observability plane (reference: stats/metric.h registry +
        # core_worker/task_event_buffer.h -> GCS task events)
        self.metrics: Dict[Tuple[str, tuple], dict] = {}
        # flight recorder: bounded structured log of runtime events
        # (node up/down, worker exits, retries, spills, stream failures
        # ...) for post-mortem debugging — the built-in replacement for
        # grepping stderr, per "Collective Communication for 100k+
        # GPUs" (arxiv 2510.20171): at pod scale a bounded in-memory
        # recorder dumped on crash is what makes failures debuggable.
        # Exposed as list_state("events"), `ray_tpu events`, dashboard
        # /api/events, and dump_flight_recorder() on fatal error.
        self.events: deque = deque(maxlen=int(self.config.runtime_events_max))
        self._event_seq = itertools.count()
        self.task_events: deque = deque(maxlen=int(self.config.task_events_max))
        self._task_event_index: Dict[bytes, dict] = {}
        # tracing spans — user spans AND the runtime's own stage spans
        # (reference: ray.util.tracing's opentelemetry spans; here they
        # land in the same timeline). The flat deque feeds the
        # chrome-trace timeline; _trace_index groups the same records
        # per trace_id for list_state("traces") / the critical-path
        # analyzer — both bounded (oldest trace evicted whole).
        self.spans: deque = deque(maxlen=int(self.config.task_events_max))
        self._trace_index: Dict[str, list] = {}
        # running per-trace summaries, maintained span-by-span so the
        # list_state("traces") overview never rescans 512x1024 span
        # dicts on the state-plane thread (evicted with the trace)
        self._trace_summaries: Dict[str, dict] = {}
        self._trace_max = 512          # distinct traces kept
        self._trace_span_max = 1024    # spans kept per trace
        # return-object id -> trace ctx for traced tasks in flight: the
        # readiness push that unparks the caller's wait() stitches into
        # the trace through this map (popped on push; FIFO-bounded)
        self._traced_oids: Dict[bytes, tuple] = {}
        # whether runtime tracing can be live at all — only consulted
        # by reactor shards to decide whether to stamp ring-entry times
        # (the state plane itself is payload-driven: a "trace" field in
        # the message is the signal, so client-mode tracing works even
        # when the head's own env has sampling off)
        from ..util.tracing import make_runtime_record, runtime_sample_rate

        self._trace_on = runtime_sample_rate() > 0.0
        # pre-bound record builder: _emit_runtime_span runs per traced
        # hub stage — the per-call `from ..util.tracing import ...`
        # lookup was measurable at sampling 1.0 (tracing_overhead row)
        self._make_runtime_record = make_runtime_record
        # ---- sampling profiler (profiling.py): folded collapsed-stack
        # counts from every process's PROFILE_BATCH flushes, keyed
        # (pid, proc kind, thread domain, stage, task, stack). Bounded
        # at profile_store_max distinct keys; overflow samples are
        # counted in _profile_drops, never stored (GL009).
        self.profile_samples: Dict[tuple, int] = {}
        self.profile_procs: Dict[int, dict] = {}
        self._profile_drops = 0
        # the hub process's OWN sampler (started in _seed_timers when
        # config-gated on) hands batches over through this SPSC ring:
        # sampler thread appends, control thread drains on a timer —
        # the same single-writer hand-off as the shard rings (GL013)
        self._profile_inbox: deque = deque()
        self._profiler = None
        # parked `ray_tpu stack` requests awaiting a worker's
        # STACK_REPLY: token -> (requester conn, req_id, worker, pid);
        # bounded and timer-expired
        self._stack_waiters: Dict[int, tuple] = {}
        self._stack_token = itertools.count(1)
        self.driver_conn = None
        self._running = True
        self._dispatching = False
        self._dispatch_pending = False
        self._pg_counter = itertools.count(1)
        self._outbox: Dict[Any, List[tuple]] = {}
        # message dispatch table, built once: {msg_type: bound _on_*
        # method}. The reactor used to resolve handlers per message via
        # getattr(self, f"_on_{msg_type}") — an f-string build plus a
        # dynamic lookup on the hottest path in the system (graftlint
        # GL007 now guards against reintroducing that shape).
        self._handlers: Dict[str, Any] = {
            name[len("_on_"):]: getattr(self, name)
            for name in dir(type(self))
            if name.startswith("_on_")
        }
        # persistent reactor selector (epoll on Linux); fds are
        # registered on accept and unregistered on disconnect instead
        # of rebuilding the interest set every tick. Created by _run —
        # it lives and dies with the reactor thread.
        self._selector: Optional[selectors.BaseSelector] = None
        # ---- multi-reactor mode (hub_shards.py): with n_shards > 1,
        # connection I/O moves to N reactor-shard threads and THIS
        # thread becomes the state plane, hosting the scheduler and
        # object-directory services behind per-shard SPSC rings.
        from .hub_shards import StateService, resolve_shard_count

        self.n_shards = resolve_shard_count(self.config.get("hub_shards", 0))
        self._shards: list = []           # ReactorShard, sharded mode only
        self._shard_rings: list = []      # shard -> state-plane rings
        self._conn_shard: Dict[Any, int] = {}  # conn -> owning shard idx
        self._state_evt = threading.Event()
        # the two internally-owned state services; both execute on the
        # state-plane thread (single consumer), reached by message only
        self.state_services = {
            "scheduler": StateService("scheduler", self._dispatch_msg),
            "objects": StateService("objects", self._dispatch_msg),
        }
        # messages drained from one peer per reactor wake before other
        # ready peers get a turn (a batch frame charges its message
        # count); the selector is level-triggered, so residual input
        # re-arms the fd and the burst continues next wake (bounded
        # fairness, not starvation). 256 = two full client batches.
        self._drain_budget = 256
        # builtin runtime metrics (ray_tpu_* namespace) record straight
        # into self.metrics — the hub IS the registry, so no RPC to
        # itself (reference: src/ray/stats/metric_defs.cc ray_* series
        # from every component). Gated: RAY_TPU_BUILTIN_METRICS=0 drops
        # the per-message timing AND keeps the registry clean.
        self._builtin_metrics = bool(self.config.builtin_metrics)
        # per-msg-type (counter, latency histogram) entries, cached so
        # the dispatch hot path pays one dict lookup, not registry math
        self._msg_metrics: Dict[str, tuple] = {}
        self._node_gauges: Dict[str, tuple] = {}
        self._seed_builtin_metrics()
        # out-of-band object plane: the head node's data-plane endpoint
        # (object_agent.py). Bulk segment bytes move through it —
        # threads of their own — so a multi-GB transfer never parks the
        # reactor behind a memcpy. Remote hosts run one inside their
        # node agent and register its endpoint.
        self.object_agent = None
        if self.config.object_agent:
            from .object_agent import ObjectAgent

            try:
                if tcp:
                    self.object_agent = ObjectAgent(
                        os.path.join(session_dir, "objects"),
                        spill_dir=self.spill_dir, host=host,
                    )
                else:
                    self.object_agent = ObjectAgent(
                        os.path.join(session_dir, "objects"),
                        spill_dir=self.spill_dir,
                        unix_path=os.path.join(session_dir, "object_agent.sock"),
                    )
                head.object_endpoint = self.object_agent.endpoint
            except OSError:
                log_exc("head object agent failed to start (relay only)")
        self._shutdown_evt = threading.Event()
        self.thread = threading.Thread(
            target=self._run if self.n_shards == 1 else self._run_sharded,
            daemon=True, name="ray-tpu-hub",
        )

    # ------------------------------------------------------------------ wire
    def start(self):
        self.thread.start()

    def _send(self, conn, msg_type: str, payload: dict):
        """Buffered send: messages accumulate per connection and are
        flushed once per drained inbound burst (up to _drain_budget
        messages) — one pickle + one syscall per peer per burst, so a
        submit storm produces one batched reply frame instead of one
        send per task. A blocking pipe write to a slow peer then
        stalls the reactor once per burst — the same reason the
        reference's raylet sends through an asio write queue."""
        q = self._outbox.get(conn)
        if q is None:
            q = self._outbox[conn] = []
        q.append((msg_type, payload))

    def _flush_outbox(self):
        if not self._outbox:
            return
        outbox, self._outbox = self._outbox, {}
        if self._shards:
            # sharded mode: each peer's socket has exactly ONE writer —
            # its owning reactor shard. Hand the batch over; the shard
            # encodes the frame (wire codec on the shard thread) and
            # counts the flush in its per-shard stats.
            shard_of = self._conn_shard
            shards = self._shards
            for conn, msgs in outbox.items():
                idx = shard_of.get(conn)
                if idx is None:
                    # peer never spoke (or already disconnected): there
                    # is no owner to write through — drop rather than
                    # interleave bytes into another shard's stream
                    continue
                shards[idx].post(conn, msgs)
            return
        for conn, msgs in outbox.items():
            self._bm_flushes["value"] += 1
            self._bm_observe(self._bm_flush_size, float(len(msgs)))
            try:
                if len(msgs) == 1:
                    conn.send_bytes(dumps_frame(msgs[0]))
                else:
                    conn.send_bytes(dumps_frame(("batch", msgs)))
            except (OSError, BrokenPipeError, EOFError):
                pass

    def _reply(self, conn, req_id: int, **payload):
        self._send(conn, P.REPLY, dict(payload, req_id=req_id))

    def _run(self):
        """The reactor: one persistent epoll/kqueue selector owns every
        fd for the hub's lifetime (the reference's asio io_context,
        instrumented_io_context.h). The previous shape re-registered
        every connection with a throwaway selector per tick
        (multiprocessing.connection.wait builds one internally) —
        O(conns) epoll_ctl syscalls per wake; now registration happens
        once per accept and teardown once per disconnect, and a wake
        costs a single epoll_wait regardless of fan-in."""
        self._seed_timers()
        self._record_event("hub_start", addr=self.addr)
        self.fairsched.bind_owner()  # single-owner discipline tripwire
        sel = self._selector = selectors.DefaultSelector()
        lsock = self.listener._listener._socket  # raw fd for readiness polling
        sel.register(lsock, selectors.EVENT_READ, None)  # data=None => accept
        try:
            self._reactor_loop(sel)
        except Exception:
            # anything escaping the per-connection guards is fatal to
            # the control plane: capture the post-mortem before the
            # session's state evaporates with this thread
            log_exc("hub reactor FATAL error")
            try:
                path = self.dump_flight_recorder("fatal_reactor_error")
                sys.stderr.write(f"[ray_tpu] flight recorder dumped to {path}\n")
            except Exception:
                log_exc("flight recorder dump failed")
        # teardown
        self._teardown_runtime()
        if self.object_agent is not None:
            self.object_agent.close()
        try:
            self.listener.close()
        except Exception:
            pass
        try:
            sel.close()
        except Exception:
            pass
        self._shutdown_evt.set()

    def _reactor_loop(self, sel) -> None:
        while self._running:
            now = time.monotonic()
            while self.timers and self.timers[0][0] <= now:
                _, _, cb = heapq.heappop(self.timers)
                try:
                    cb()
                except Exception:
                    log_exc("hub timer error")
            self._flush_outbox()
            timeout = None
            if self.timers:
                timeout = max(0.0, self.timers[0][0] - time.monotonic())
            events = sel.select(timeout)
            self._bm_wakeups["value"] += 1
            for key, _mask in events:
                conn = key.data
                if conn is None:
                    try:
                        conn = self.listener.accept()
                        sel.register(conn, selectors.EVENT_READ, conn)
                    except Exception:
                        log_exc("hub accept error")
                    continue
                try:
                    # Drain this peer's burst to exhaustion — bounded:
                    # after _drain_budget frames, other ready peers get
                    # their turn and the level-triggered selector
                    # re-arms this fd for the remainder. Replies are
                    # buffered across the whole burst and flushed ONCE,
                    # so a 128-task submit storm produces one batched
                    # reply frame per peer instead of 128 sends.
                    budget = self._drain_budget
                    while True:
                        blob = conn.recv_bytes()
                        msg_type, payload = loads_frame(blob)
                        try:
                            self._handle(conn, msg_type, payload)
                        except Exception:
                            # A handler bug must never kill the control plane.
                            log_exc(f"hub handler error on {msg_type}")
                        # budget is counted in MESSAGES, not frames — a
                        # ("batch", [...]) frame carries up to 128, and
                        # charging it as 1 would let one peer hold the
                        # reactor for 128x the intended fairness bound
                        budget -= len(payload) if msg_type == "batch" else 1
                        if budget <= 0:
                            if conn.poll(0):
                                self._bm_drain_sat["value"] += 1
                            break
                        if not conn.poll(0):
                            break
                    self._flush_outbox()
                except (EOFError, OSError):
                    self._safe_disconnect(conn)
                except Exception:
                    # a stray bug in the recv/dispatch path must cost
                    # one connection, never the reactor thread — every
                    # client in the session hangs if this loop dies
                    log_exc("hub reactor error (dropping conn)")
                    self._safe_disconnect(conn)

    # ------------------------------------------------ sharded control plane
    def _seed_timers(self) -> None:
        """Periodic jobs shared by BOTH control-plane topologies — a
        timer added here runs with shards=1 and shards>1 alike."""
        self._add_timer(self.config.worker_reap_period_s, self._reap_workers)
        if self.config.memory_usage_threshold > 0:
            self._add_timer(
                self.config.memory_monitor_period_s, self._memory_monitor
            )
        if self.config.node_heartbeat_period_s > 0:
            self._add_timer(
                self.config.node_heartbeat_period_s, self._head_heartbeat
            )
            if self.config.node_heartbeat_miss_threshold > 0:
                self._add_timer(
                    self.config.node_heartbeat_period_s,
                    self._check_node_heartbeats,
                )
        if self._chaos is not None:
            # (re-)anchor the schedule clock to the control plane start
            self._chaos.arm()
            if self._chaos.timed:
                self._add_timer(0.05, self._chaos_tick)
        # hub-process sampler (profiling.py; default off — with
        # profile_hz 0 maybe_start creates nothing and no timer is
        # armed). In the local driver the process sampler may already
        # belong to the driver client; first caller wins and both sinks
        # see the same threads.
        from . import profiling as _profiling

        self._profiler = _profiling.maybe_start(
            "hub", self._profile_inbox.append,
            hz=self.config.get("profile_hz", 0.0),
            budget=self.config.get("profile_overhead_budget", 0.03),
            flush_period=self.config.get("profile_flush_period_s", 1.0),
        )
        if self._profiler is not None:
            self._add_timer(
                self._profiler.flush_period, self._drain_profile_inbox
            )

    def _teardown_runtime(self) -> None:
        """Shared epilogue: stop workers/agents and flush the last
        replies (both topologies run this before closing their I/O)."""
        for w in self.workers.values():
            self._kill_worker(w)
        for conn in list(self.agent_conns):
            self._send(conn, P.KILL, {})
        self._flush_outbox()
        # Drop pending one-shot timers: after teardown their callbacks
        # would fire into freed worker/agent tables (GL016).
        self.timers.clear()
        if self._profiler is not None:
            from . import profiling as _profiling

            _profiling.stop()
            self._profiler = None

    def _run_sharded(self):
        """State-plane main loop (n_shards > 1): reactor shards own the
        sockets; this thread owns every table and both state services.
        Mirrors _run's lifecycle (timers, fatal-error flight dump,
        teardown) with socket I/O delegated to the shards."""
        from .hub_shards import ReactorShard, ShardRing

        self._seed_timers()
        self._record_event("hub_start", addr=self.addr, shards=self.n_shards)
        self.fairsched.bind_owner()  # this thread IS the state plane
        rings = self._shard_rings = [
            ShardRing(self._state_evt.set) for _ in range(self.n_shards)
        ]
        shards = self._shards = [
            ReactorShard(
                i, rings[i], self._drain_budget,
                listener=self.listener if i == 0 else None,
                trace_on=self._trace_on,
            )
            for i in range(self.n_shards)
        ]
        for s in shards:
            s.peers = shards
        for s in shards:
            s.start()
        try:
            self._state_loop(rings)
        except Exception:
            log_exc("hub state plane FATAL error")
            try:
                path = self.dump_flight_recorder("fatal_state_plane_error")
                sys.stderr.write(f"[ray_tpu] flight recorder dumped to {path}\n")
            except Exception:
                log_exc("flight recorder dump failed")
        # teardown — the shared epilogue, then stop the shards (each
        # flushes its outbound ring once more so the KILLs get out)
        self._teardown_runtime()
        for s in shards:
            s.stop()
        for s in shards:
            s.join(timeout=2.0)
        for s in shards:
            if not s.is_alive():
                # nothing can post to a joined shard: safe to release
                # its wake pipe (closing earlier risks a write into a
                # recycled fd number)
                s.close_wakeups()
        if self.object_agent is not None:
            self.object_agent.close()
        try:
            self.listener.close()
        except Exception:
            pass
        for conn in list(self._conn_shard):
            try:
                conn.close()
            except Exception:
                pass
        self._conn_shard.clear()
        self._shutdown_evt.set()

    def _state_loop(self, rings) -> None:
        from .hub_shards import CONN_LOST, SHARD_EVENT

        services = self.state_services
        while self._running:
            now = time.monotonic()
            while self.timers and self.timers[0][0] <= now:
                _, _, cb = heapq.heappop(self.timers)
                try:
                    cb()
                except Exception:
                    log_exc("hub timer error")
            self._flush_outbox()
            timeout = None
            if self.timers:
                timeout = max(0.0, self.timers[0][0] - time.monotonic())
            self._state_evt.wait(timeout)
            self._state_evt.clear()
            self._bm_wakeups["value"] += 1
            for idx, ring in enumerate(rings):
                for conn, service, msg_type, payload in ring.drain():
                    if msg_type == CONN_LOST:
                        self._conn_shard.pop(conn, None)
                        self._safe_disconnect(conn)
                        continue
                    if msg_type == SHARD_EVENT:
                        fields = dict(payload)
                        kind = fields.pop("kind")
                        self._record_event(kind, **fields)
                        if kind == "shard_fatal":
                            # a dead shard would otherwise half-kill the
                            # hub: accepts stop (shard 0) or 1-in-N new
                            # conns adopt into a ring nobody drains.
                            # Fail LOUDLY like the single-reactor fatal
                            # path: dump the post-mortem and tear the
                            # session down so every peer sees EOF.
                            log_exc_msg = (
                                f"[ray_tpu] hub shard {fields.get('shard')} "
                                "died; shutting the control plane down\n"
                            )
                            sys.stderr.write(log_exc_msg)
                            try:
                                path = self.dump_flight_recorder(
                                    "shard_fatal")
                                sys.stderr.write(
                                    f"[ray_tpu] flight recorder dumped "
                                    f"to {path}\n")
                            except Exception:
                                log_exc("flight recorder dump failed")
                            self._running = False
                        continue
                    self._conn_shard[conn] = idx
                    try:
                        # per-frame guard, like the single-reactor loop:
                        # a handler bug costs one frame, never the plane
                        self._handle_sharded(conn, service, msg_type,
                                             payload, services)
                    except Exception:
                        log_exc(f"hub state-plane error on {msg_type}")
            self._flush_outbox()

    def _handle_sharded(self, conn, service, msg_type, payload,
                        services) -> None:
        """_handle's sharded twin: route one shard-delivered message to
        its state service. Chaos shares _handle's single decision point
        (outer msg_type only, on the state-plane thread — so the seeded
        decision sequence is identical under both topologies); batch
        frames fan their inner messages out to each message's owning
        service, preserving arrival order. The only intended divergence
        from _handle is the per-service accounting seam
        (StateService.handle)."""
        trace_on = self._trace_on  # shards only stamp when sampling is on
        if trace_on:
            # pop ring stamps BEFORE the chaos seam: the ring crossing
            # already happened (the span is valid even for a frame chaos
            # then drops), and a delayed/dup redelivery must not carry a
            # stale stamp into its handler
            if msg_type == "batch":
                for _mt, pl in payload:
                    if type(pl) is dict and "_ring_t" in pl:
                        self._ring_wait_span(conn, pl)
            elif type(payload) is dict and "_ring_t" in payload:
                self._ring_wait_span(conn, payload)
        if self._chaos is not None and self._chaos_intercept(
            conn, msg_type, payload
        ):
            return  # injected drop/delay (redelivery is timer-driven)
        if msg_type == "batch":
            for mt, pl in payload:
                self._route_to_service(conn, mt, pl)
            return
        services.get(service, services["scheduler"]).handle(
            conn, msg_type, payload
        )

    def _route_to_service(self, conn, msg_type, payload) -> None:
        """Route one (non-batch) message to its owning StateService by
        SERVICE_OF — the ONE ownership rule batch fan-out and chaos
        redelivery share. (The non-batch ring path routes by the
        shard's service tag instead, which the shard derived from the
        same table.)"""
        from .hub_shards import SERVICE_OF

        svc = self.state_services[
            "objects" if SERVICE_OF.get(msg_type) == "objects"
            else "scheduler"
        ]
        svc.handle(conn, msg_type, payload)

    def _ring_wait_span(self, conn, payload: dict) -> None:
        """A traced message crossed a shard's SPSC ring: the owning
        shard stamped its decode time (hub_shards._stamp_trace, the
        shard's ONLY involvement — it never touches this span store,
        GL010); the delta to now is the ring-wait stage."""
        t_ring = payload.pop("_ring_t", None)
        tr = payload.get("trace")
        if t_ring is None or tr is None:
            return
        req_id = payload.get("req_id")
        if req_id is not None and (id(conn), req_id) in self._inflight_reqs:
            return  # retransmit of a parked request: one crossing span
        self._emit_runtime_span(
            "shard.ring_wait", "ring_wait", (tr[0], tr[1]),
            t_ring, time.monotonic(),
        )

    def _merge_shard_metrics(self) -> None:
        """Fold per-shard reactor counters (written only by their shard
        threads; read-only here) into the registry as shard-labelled
        builtin series, plus per-service message counts. Called at
        scrape time (list_state("metrics") / flight dump) so the hot
        path never pays for the merge. Single-reactor mode keeps the
        original untagged series untouched."""
        if not self._shards or not self._builtin_metrics:
            return
        for s in self._shards:
            # scrape-time read of the shard's monotonic counters: each
            # field is written only by its shard thread and is a plain
            # int (GIL-atomic load) — worst case one bump stale, never
            # torn. The documented merge-at-scrape pattern (README
            # "sharded control plane"), not a missing lock.
            st = s.stats  # graftlint: disable=GL013 — scrape-time monotonic counter read
            tags = (("shard", str(s.idx)),)
            self._bm(
                "ray_tpu_hub_reactor_wakeups_total", "counter",
                "reactor selector wake-ups", tags,
            )["value"] = float(st.wakeups)
            self._bm(
                "ray_tpu_hub_drain_budget_saturated_total", "counter",
                "bursts cut off by the per-peer drain budget with input "
                "still pending", tags,
            )["value"] = float(st.drain_saturated)
            self._bm(
                "ray_tpu_hub_outbox_flushes_total", "counter",
                "per-peer outbox flushes (one frame each)", tags,
            )["value"] = float(st.frames_sent)
            self._bm(
                "ray_tpu_hub_shard_conns", "gauge",
                "connections owned by this reactor shard", tags,
            )["value"] = float(st.conns)
            m = self._bm(
                "ray_tpu_hub_outbox_flush_messages", "histogram",
                "messages coalesced per outbox flush", tags,
                _ShardStats.FLUSH_BOUNDS,
            )
            m["sum"] = st.flush_sum
            m["count"] = st.flush_count
            for pair, c in zip(m["buckets"], st.flush_buckets):
                pair[1] = c
        for name, svc in self.state_services.items():
            self._bm(
                "ray_tpu_state_service_messages_total", "counter",
                "messages handled by this state service",
                (("service", name),),
            )["value"] = float(svc.processed)

    def _head_heartbeat(self) -> None:
        """Self-sample the head node's gauges (remote hosts report the
        same numbers via node-agent heartbeats, _on_node_heartbeat)."""
        head = self.nodes.get("node0")
        if head is not None:
            rss = self._worker_rss(os.getpid()) + sum(
                self._worker_rss(w.proc.pid)
                for w in self.workers.values()
                if w.proc is not None and w.node_id == "node0"
            )
            try:
                load = os.getloadavg()[0]
            except OSError:
                load = 0.0
            self._node_stat_gauges(
                "node0",
                rss_bytes=float(rss),
                cpu_load_1m=load,
                n_workers=float(sum(
                    1 for w in self.workers.values() if w.node_id == "node0"
                )),
            )
            self._bm_store_gauge(head)
            if self.object_agent is not None:
                self._object_direct_gauges("node0", self.object_agent.stats())
        self._add_timer(self.config.node_heartbeat_period_s, self._head_heartbeat)

    def _node_stat_gauges(self, node_id: str, **stats: float) -> None:
        tags = (("node_id", node_id),)
        for name, value in stats.items():
            self._bm(f"ray_tpu_node_{name}", "gauge",
                     "node-agent heartbeat stat", tags)["value"] = value

    def _on_node_heartbeat(self, conn, p):
        node = self.nodes.get(p.get("node_id", ""))
        if node is None or not node.alive:
            return
        node.last_heartbeat_t = time.monotonic()
        self._node_stat_gauges(
            node.node_id,
            rss_bytes=float(p.get("rss_bytes", 0.0)),
            cpu_load_1m=float(p.get("cpu_load_1m", 0.0)),
            n_workers=float(p.get("n_workers", 0.0)),
        )
        if p.get("object_agent"):
            self._object_direct_gauges(node.node_id, p["object_agent"])
        self._bm_store_gauge(node)

    def _add_timer(self, delay: float, cb):
        heapq.heappush(self.timers, (time.monotonic() + delay, next(self._timer_seq), cb))

    # ------------------------------------------- builtin runtime metrics
    # handler latencies are tens of µs; placement can take seconds when
    # a worker must spawn; flush sizes are message counts. The flush
    # bounds are THE shared constant (hub_shards.ShardStats) so the
    # per-shard bucket merge in _merge_shard_metrics can never zip
    # against mismatched boundaries.
    _LATENCY_BOUNDS = (50e-6, 200e-6, 1e-3, 5e-3, 25e-3, 0.1, 1.0)
    _PLACEMENT_BOUNDS = (1e-3, 5e-3, 25e-3, 0.1, 0.5, 2.0, 10.0)
    _FLUSH_BOUNDS = _ShardStats.FLUSH_BOUNDS

    def _bm(self, name: str, mtype: str, description: str = "",
            tags: tuple = (), boundaries: tuple = ()) -> dict:
        """Get-or-create a builtin registry entry — the same dict shape
        _on_metric_record aggregates into, so builtin series ride the
        existing snapshot()/prometheus_text()/dashboard surfaces for
        free. With builtin metrics disabled the entry is a detached
        dict: update paths stay branch-free, the registry stays clean."""
        if not self._builtin_metrics:
            return {"name": name, "type": mtype, "description": description,
                    "tags": tags, "value": 0.0, "sum": 0.0, "count": 0,
                    "buckets": [[b, 0] for b in boundaries]}
        key = (name, tags)
        m = self.metrics.get(key)
        if m is None:
            m = self.metrics[key] = {
                "name": name, "type": mtype, "description": description,
                "tags": tags, "value": 0.0, "sum": 0.0, "count": 0,
                "buckets": [[b, 0] for b in boundaries],
            }
        return m

    @staticmethod
    def _bm_observe(m: dict, value: float) -> None:
        m["sum"] += value
        m["count"] += 1
        for pair in m["buckets"]:
            if value <= pair[0]:
                pair[1] += 1
                break

    def _seed_builtin_metrics(self) -> None:
        """Pre-register the untagged builtin series (and cache direct
        entry references for the hot paths) so a scrape sees the full
        catalog at zero even before the first increment."""
        bm = self._bm
        self._bm_wakeups = bm(
            "ray_tpu_hub_reactor_wakeups_total", "counter",
            "reactor selector wake-ups")
        self._bm_drain_sat = bm(
            "ray_tpu_hub_drain_budget_saturated_total", "counter",
            "bursts cut off by the per-peer drain budget with input "
            "still pending")
        self._bm_flushes = bm(
            "ray_tpu_hub_outbox_flushes_total", "counter",
            "per-peer outbox flushes (one frame each)")
        self._bm_flush_size = bm(
            "ray_tpu_hub_outbox_flush_messages", "histogram",
            "messages coalesced per outbox flush",
            boundaries=self._FLUSH_BOUNDS)
        self._bm_queue_depth = bm(
            "ray_tpu_scheduler_queue_depth", "gauge",
            "runnable tasks queued across scheduling classes")
        self._bm_placement = bm(
            "ray_tpu_scheduler_placement_latency_seconds", "histogram",
            "submit-to-dispatch latency", boundaries=self._PLACEMENT_BOUNDS)
        self._bm_placed = bm(
            "ray_tpu_scheduler_tasks_placed_total", "counter",
            "tasks dispatched to a worker")
        self._bm_spawns = bm(
            "ray_tpu_scheduler_worker_spawns_total", "counter",
            "worker processes spawned")
        self._bm_task_fail = bm(
            "ray_tpu_tasks_failed_total", "counter",
            "tasks failed past their retry budget")
        self._bm_task_retry = bm(
            "ray_tpu_tasks_retried_total", "counter",
            "task retries (worker death or retry_exceptions)")
        self._bm_spills = bm(
            "ray_tpu_object_store_spilled_total", "counter",
            "shm segments spilled to disk")
        self._bm_restores = bm(
            "ray_tpu_object_store_restored_total", "counter",
            "spilled segments restored to shm")
        self._bm_credit_stalls = bm(
            "ray_tpu_stream_credit_stalls_total", "counter",
            "streaming-generator producers parked on backpressure credit")
        self._bm_events_total = bm(
            "ray_tpu_events_total", "counter",
            "flight-recorder events recorded")
        self._bm_preemptions = bm(
            "ray_tpu_sched_preemptions_total", "counter",
            "gangs (placement groups / tasks) preempted for "
            "higher-priority reservations")
        self._bm_pending_quota = bm(
            "ray_tpu_sched_pending_quota", "gauge",
            "tasks parked at admission by their tenant's quota")
        self._bm_obj_fallbacks = bm(
            "ray_tpu_object_fallbacks_total", "counter",
            "direct object transfers that fell back to the hub relay")
        # (oid, kind, reason) seen recently — a retransmitted first
        # chunk must not double-count its transfer's fallback
        self._fallback_seen: Dict[tuple, bool] = {}

    def _record_fallback(self, oid: bytes, reason: str, kind: str) -> None:
        """One direct-path transfer failed over to the hub relay:
        flight-recorder event + ray_tpu_object_fallbacks_total."""
        key = (oid, kind, reason)
        if key in self._fallback_seen:
            return  # retransmit of the same flagged chunk
        self._fallback_seen[key] = True
        while len(self._fallback_seen) > 1024:
            self._fallback_seen.pop(next(iter(self._fallback_seen)))
        self._bm_obj_fallbacks["value"] += 1
        self._record_event(
            "object_transfer_fallback",
            object_id=oid.hex() if isinstance(oid, bytes) else str(oid),
            op=kind, reason=str(reason)[:200],
        )

    def _object_direct_gauges(self, node_id: str, stats: dict) -> None:
        """Per-node out-of-band transfer counters (served + received
        bytes move through object agents, never this reactor — the
        numbers arrive on heartbeats)."""
        tags = (("node_id", node_id),)
        self._bm("ray_tpu_object_direct_bytes", "counter",
                 "bytes moved over the out-of-band object plane",
                 tags)["value"] = float(
            stats.get("bytes_served", 0) + stats.get("bytes_received", 0)
        )
        self._bm("ray_tpu_object_direct_transfers_total", "counter",
                 "completed out-of-band object transfers",
                 tags)["value"] = float(stats.get("transfers", 0))

    def _bm_store_gauge(self, node: NodeEntry) -> None:
        g = self._node_gauges.get(node.node_id)
        if g is None:
            tags = (("node_id", node.node_id),)
            g = self._node_gauges[node.node_id] = (
                self._bm("ray_tpu_object_store_bytes", "gauge",
                         "live shm segment bytes", tags),
                self._bm("ray_tpu_node_chips_in_use", "gauge",
                         "TPU chips not in the node's free pool", tags),
            )
        g[0]["value"] = node.store_used
        g[1]["value"] = float(
            node.total.get("TPU", 0.0)
        ) - len(node.free_tpu_chips)

    # ------------------------------------------------ flight recorder
    @staticmethod
    def _trace_fields(spec) -> dict:
        """Flight-recorder cross-link: when the task at hand is traced,
        its events (task_retry/task_failed/preemption/...) carry the
        trace_id so `ray_tpu events` and `ray_tpu trace` join up."""
        if spec is not None and spec.trace is not None:
            return {"trace_id": spec.trace[0]}
        return {}

    def _record_event(self, kind: str, **fields) -> None:
        ev = {"seq": next(self._event_seq), "ts": time.time(), "kind": kind}
        ev.update(fields)
        self.events.append(ev)
        self._bm_events_total["value"] += 1

    def _flight_doc(self, reason: str) -> dict:
        try:
            self._merge_shard_metrics()
        except Exception:
            pass  # post-mortem must survive a half-torn-down shard set
        return {
            "reason": reason,
            "dumped_at": time.time(),
            "shards": self.n_shards,
            # copy every row: json.dump runs AFTER the retry window, so
            # handing it live dicts the reactor still mutates would
            # reintroduce the mid-iteration crash the retry guards
            "events": [dict(e) for e in self.events],
            "metrics": [
                dict(m, tags=[list(t) for t in m["tags"]],
                     buckets=[list(b) for b in m["buckets"]])
                for m in list(self.metrics.values())
            ],
            "nodes": [
                {"node_id": n.node_id, "alive": n.alive, "ip": n.ip,
                 "resources": dict(n.total), "available": dict(n.avail),
                 "store_used": n.store_used}
                for n in list(self.nodes.values())
            ],
            "workers": [
                {"worker_id": w.worker_id, "state": w.state,
                 "node_id": w.node_id,
                 "pid": w.proc.pid if w.proc else None}
                for w in list(self.workers.values())
            ],
            "tasks": [dict(e) for e in list(self.task_events)[-200:]],
        }

    def dump_flight_recorder(self, reason: str = "manual") -> str:
        """Write events + registry + cluster tables to disk for
        post-mortem (called on reactor fatal error and head SIGTERM;
        RAY_TPU_FLIGHT_RECORDER_PATH overrides the session-dir default).

        Callable from any thread: the reactor keeps mutating these
        structures while a SIGTERM handler or driver snapshots them, so
        a mid-iteration resize (RuntimeError) is retried — losing the
        post-mortem exactly when the system is busy defeats its point."""
        import json as _json

        path = (self.config.get("flight_recorder_path") or "").strip()
        if not path:
            path = os.path.join(self.session_dir, "flight_recorder.json")
        for attempt in range(4):
            try:
                doc = self._flight_doc(reason)
                break
            except RuntimeError:
                if attempt == 3:
                    raise
                time.sleep(0.05)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            _json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return path

    # ------------------------------------------------- fault injection
    # (chaos.py engine, hub scope). All methods below are reached only
    # behind `if self._chaos is not None` — the inert default costs one
    # attribute load per inbound frame.
    def _chaos_trace(self, msg_type: str, payload) -> dict:
        """trace_id cross-link for a fault event, when the victim
        message is traced — a fault then shows up inside its victim's
        trace via the PR 8 events<->trace join."""
        if msg_type != "batch" and type(payload) is dict:
            tr = payload.get("trace")
            if tr is not None:
                return {"trace_id": tr[0]}
        return {}

    def _chaos_intercept(self, conn, msg_type: str, payload) -> bool:
        """The ONE message-fault decision point both topologies share:
        drop/delay/dup are decided against the frame's OUTER msg_type
        (batch frames fault whole, never per inner message), and a
        partitioned node's conns are blackholed wholesale. Returns True
        when the frame must NOT be dispatched now."""
        eng = self._chaos
        if eng.partitions:
            nid = self.agent_conns.get(conn)
            if nid is None:
                wid = self.conn_to_worker.get(conn)
                if wid is not None:
                    w = self.workers.get(wid)
                    nid = w.node_id if w is not None else None
            if nid is not None and eng.partition_active(nid):
                eng.record("partition_drop", node_id=nid, msg_type=msg_type)
                self._record_event(
                    "chaos_partition_drop", node_id=nid, msg_type=msg_type,
                )
                return True
        act = eng.message_action(msg_type)
        if act is None:
            return False
        kind = act[0]
        if kind == "drop":
            self._record_event(
                "chaos_drop", msg_type=msg_type,
                **self._chaos_trace(msg_type, payload),
            )
            return True
        if kind == "delay":
            self._record_event(
                "chaos_delay", msg_type=msg_type, delay_s=round(act[1], 6),
                **self._chaos_trace(msg_type, payload),
            )
            self._add_timer(
                act[1],
                lambda c=conn, mt=msg_type, pl=payload:
                    self._dispatch_after_chaos(c, mt, pl),
            )
            return True
        # dup: deliver the duplicate first, then fall through to the
        # normal dispatch — exercises the retransmit-dedup and
        # idempotent-handler paths exactly like a replayed frame
        self._record_event(
            "chaos_dup", msg_type=msg_type,
            **self._chaos_trace(msg_type, payload),
        )
        self._dispatch_after_chaos(conn, msg_type, payload)
        return False

    def _dispatch_after_chaos(self, conn, msg_type: str, payload) -> None:
        """Chaos-exempt redelivery (the delayed copy / the duplicate):
        a second engine pass would re-draw and could delay forever.
        Sharded mode routes through the owning StateService so the
        per-service accounting seam counts redelivered frames exactly
        like first deliveries (timers run on the state-plane thread,
        the services' single owner)."""
        if getattr(conn, "closed", False):
            # the peer disconnected inside the delay window (both
            # topologies close the conn in _safe_disconnect): replaying
            # now would re-register the dead conn in stateful handlers
            # (_on_hello inserting it into client_conns/workers), and
            # no second CONN_LOST ever prunes it
            return
        try:
            if self._shards:
                if msg_type == "batch":
                    for mt, pl in payload:
                        self._route_to_service(conn, mt, pl)
                else:
                    self._route_to_service(conn, msg_type, payload)
            elif msg_type == "batch":
                for mt, pl in payload:
                    self._dispatch_msg(conn, mt, pl)
            else:
                self._dispatch_msg(conn, msg_type, payload)
        except Exception:
            log_exc(f"hub handler error on {msg_type} (chaos redelivery)")

    def _chaos_tick(self) -> None:
        """Execute due timed faults (conn_kill / worker_kill /
        worker_hang) against the live cluster tables; a fault with no
        eligible victim yet is deferred, not dropped — the schedule is
        the plan's, the victims are whatever the cluster offers."""
        eng = self._chaos
        for fault in list(eng.due_faults()):
            try:
                self._apply_timed_fault(eng, fault)
            except Exception:
                log_exc(f"chaos fault {fault.kind} failed")
                eng.consume(fault, fault.count - fault.fired)
        if eng.timed:
            self._add_timer(0.05, self._chaos_tick)

    def _apply_timed_fault(self, eng, fault) -> None:
        if fault.kind == "conn_kill":
            if fault.arg == "worker":
                victims = [
                    w.conn
                    for _, w in sorted(self.workers.items())
                    if w.conn is not None
                ]
            else:
                # established (post-grace) non-driver clients, oldest
                # first: a kill inside the HELLO->first-reply window
                # would test the connect race, not recovery
                now = time.monotonic()
                victims = [
                    c for c, (_seq, t0) in sorted(
                        self.client_conns.items(), key=lambda kv: kv[1][0]
                    )
                    if c is not self.driver_conn and now - t0 >= 0.5
                ]
            if not victims:
                eng.defer(fault)
                return
            eng.record("conn_kill", role=fault.arg)
            self._record_event("chaos_conn_kill", role=fault.arg)
            eng.consume(fault)
            self._expel_conn(victims[0])
            return
        # worker_kill / worker_hang: busy plain-task workers first (a
        # fault plane exists to hit in-flight work), then actors, then
        # idle pool members — ordered by worker id within each tier
        hang = fault.kind == "worker_hang"
        _tier = {"busy": 0, "actor": 1}

        def _reachable(w) -> bool:
            # hub-local proc handle, or a live agent that holds one
            # (remote faults ride P.KILL_WORKER with a sig field)
            if w.proc is not None:
                return True
            node = self.nodes.get(w.node_id)
            return (node is not None and node.alive
                    and node.agent_conn is not None)

        candidates = sorted(
            (w for w in self.workers.values()
             if w.conn is not None and _reachable(w)
             and w.state in ("busy", "actor", "idle")),
            key=lambda w: (_tier.get(w.state, 2), w.worker_id),
        )
        want = fault.count - fault.fired
        if not candidates:
            eng.defer(fault)
            return
        for w in candidates[:want]:
            spec = w.current_task
            fields = {
                "worker_id": w.worker_id, "node_id": w.node_id,
                **self._trace_fields(spec),
            }
            if spec is not None:
                fields["task_id"] = spec.task_id.hex()
            eng.record(fault.kind, worker_id=w.worker_id)
            self._record_event(f"chaos_{fault.kind}", **fields)
            eng.consume(fault)
            # "stop" = SIGSTOP: the process stalls mid-instruction but
            # its socket stays open — only the hung-worker watchdog /
            # per-task timeout_s can recover this. No _expel_conn here:
            # chaos leaves discovery to the runtime's own recovery.
            self._deliver_worker_signal(w, "stop" if hang else "kill")
        if fault.fired < fault.count:
            eng.defer(fault)

    def _expel_conn(self, conn) -> None:
        """Forcibly drop one peer connection (chaos conn_kill, or the
        heartbeat-miss watchdog evicting a partitioned node's agent).
        The peer sees EOF; registries clean up through the normal
        disconnect path."""
        if self._shards:
            idx = self._conn_shard.get(conn)
            if idx is not None:
                # the owning shard must do the unregister (its selector,
                # its thread); cleanup comes back as CONN_LOST
                self._shards[idx].expel(conn)
                return
        self._safe_disconnect(conn)

    def _check_node_heartbeats(self) -> None:
        """Heartbeat-miss node death (reference: GcsNodeManager's
        heartbeat timeout): an agent whose heartbeats stopped — network
        partition, frozen host — is declared dead after the configured
        number of missed periods; its conn is expelled so the normal
        node-death path (task retry elsewhere, reconstruction,
        __node_down__ invalidation) runs. Conn EOF remains the fast
        path; this catches the silent half-open case."""
        period = self.config.node_heartbeat_period_s
        limit = self.config.node_heartbeat_miss_threshold * period
        now = time.monotonic()
        for node in list(self.nodes.values()):
            if node.agent_conn is None or not node.alive:
                continue
            if node.last_heartbeat_t and now - node.last_heartbeat_t > limit:
                missed = (now - node.last_heartbeat_t) / period
                sys.stderr.write(
                    f"[ray_tpu] node {node.node_id}: no heartbeat for "
                    f"{missed:.1f} periods; declaring it dead\n"
                )
                self._record_event(
                    "node_heartbeat_miss", node_id=node.node_id,
                    missed_periods=round(missed, 1),
                )
                self._expel_conn(node.agent_conn)
        self._add_timer(period, self._check_node_heartbeats)

    # -------------------------------------------------------------- dispatch
    def _handle(self, conn, msg_type: str, payload):
        """Table dispatch against the {msg_type: bound_method} map built
        in __init__ (no per-message reflection — GL007)."""
        if self._chaos is not None and self._chaos_intercept(
            conn, msg_type, payload
        ):
            return  # injected drop/delay (redelivery is timer-driven)
        if msg_type == "batch":
            for mt, pl in payload:
                self._dispatch_msg(conn, mt, pl)
            return
        self._dispatch_msg(conn, msg_type, payload)

    def _dispatch_msg(self, conn, msg_type: str, payload) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            return
        if not self._builtin_metrics:
            handler(conn, payload)
            return
        mm = self._msg_metrics.get(msg_type)
        if mm is None:
            tags = (("type", msg_type),)
            mm = self._msg_metrics[msg_type] = (
                self._bm("ray_tpu_hub_messages_total", "counter",
                         "messages handled, by type", tags),
                self._bm("ray_tpu_hub_handler_latency_seconds", "histogram",
                         "handler wall time, by message type", tags,
                         self._LATENCY_BOUNDS),
            )
        t0 = time.perf_counter()
        handler(conn, payload)
        dt = time.perf_counter() - t0
        mm[0]["value"] += 1
        self._bm_observe(mm[1], dt)

    def _ordered_nodes(self) -> List[NodeEntry]:
        """Alive nodes, head first (the hybrid policy's prefer-local)."""
        out = []
        head = self.nodes.get("node0")
        if head is not None and head.alive:
            out.append(head)
        for nid in sorted(self.nodes):
            n = self.nodes[nid]
            if n.alive and n is not head:
                out.append(n)
        return out

    def _node_worker_count(self, node_id: str) -> int:
        """Workers counted against the node's POOLED task-worker cap —
        actor-bound workers don't count (actors always get processes;
        the reference likewise grows its pool for actors rather than
        letting pinned actors starve task execution)."""
        return sum(
            1 for w in self.workers.values()
            if w.node_id == node_id
            and w.actor_id is None
            and not (
                w.current_task is not None and w.current_task.is_actor_create
            )
        )

    def _on_hello(self, conn, p):
        if p["role"] == "worker":
            wid = p["worker_id"]
            w = self.workers.get(wid)
            if w is None:
                w = WorkerEntry(worker_id=wid, node_id=p.get("node_id", "node0"))
                self.workers[wid] = w
            w.conn = conn
            w.state = "idle"
            w.pid = p.get("pid")
            w.connected_t = time.monotonic()
            self.conn_to_worker[conn] = wid
            node = self.nodes.get(w.node_id)
            if node is not None:
                node.spawning = max(0, node.spawning - 1)
                if w.spawned_for_actor:
                    node.spawning_actor = max(0, node.spawning_actor - 1)
            self._dispatch()
        elif p["role"] == "driver":
            self.driver_conn = conn
            self.client_conns[conn] = (
                next(self._client_conn_seq), time.monotonic(),
            )
        elif p["role"] == "client":
            # a remote driver (Ray Client parity) — its disconnect must
            # NOT tear the session down. Tracked (HELLO order) so chaos
            # conn_kill has a deterministic victim ordering.
            self.client_conns[conn] = (
                next(self._client_conn_seq), time.monotonic(),
            )

    def _on_register_node(self, conn, p):
        node = NodeEntry(
            node_id=p["node_id"],
            hostname=p["hostname"],
            ip=p["ip"],
            session_dir=p["session_dir"],
            total=dict(p["resources"]),
            avail=dict(p["resources"]),
            free_tpu_chips=set(p.get("tpu_chip_ids", [])),
            chip_coords={
                int(k): tuple(v)
                for k, v in (p.get("tpu_chip_coords") or {}).items()
            },
            max_workers=p.get("max_workers") or 4,
            agent_conn=conn,
            store_cap=float(p.get("store_cap") or 0),
            object_endpoint=p.get("object_endpoint") or "",
            last_heartbeat_t=time.monotonic(),
        )
        # dead nodes stay as tombstones for introspection/lineage
        self.nodes[node.node_id] = node  # graftlint: disable=GL009
        self.agent_conns[conn] = node.node_id
        self._record_event(
            "node_up", node_id=node.node_id, hostname=node.hostname,
            ip=node.ip, resources=dict(node.total),
        )
        self._reply(conn, p["req_id"], ok=True)
        self._dispatch()

    def _on_worker_exited(self, conn, p):
        """Agent-reported child death before the worker ever connected
        (post-connect deaths surface as conn EOF)."""
        w = self.workers.get(p["worker_id"])
        if w is not None and w.conn is None:
            node = self.nodes.get(w.node_id)
            if node is not None:
                node.spawning = max(0, node.spawning - 1)
                if w.spawned_for_actor:
                    node.spawning_actor = max(0, node.spawning_actor - 1)
            sys.stderr.write(
                f"[ray_tpu] worker {w.worker_id} on {w.node_id} exited with "
                f"code {p.get('code')} before connecting\n"
            )
            self._record_event(
                "worker_spawn_failed", worker_id=w.worker_id,
                node_id=w.node_id, code=p.get("code"),
            )
            self.workers.pop(w.worker_id, None)
            self._dispatch()

    # ----- objects
    def _conn_node(self, conn) -> str:
        wid = self.conn_to_worker.get(conn)
        if wid is not None:
            w = self.workers.get(wid)
            if w is not None:
                return w.node_id
        return "node0"  # driver and hub live on the head node

    def _conn_label(self, conn) -> str:
        """Stable human-readable identity of a peer for ownership
        attribution: a worker id, "driver", "client-N" (HELLO order),
        or "hub" for hub-internal calls (conn=None)."""
        if conn is None:
            return "hub"
        wid = self.conn_to_worker.get(conn)
        if wid is not None:
            return wid
        if conn is self.driver_conn:
            return "driver"
        ent = self.client_conns.get(conn)
        if ent is not None:
            return f"client-{ent[0]}"
        return ""

    def _owner_alive(self, owner: str) -> bool:
        """Does the owning process still hold a live control conn? A
        ready object whose owner is gone can never be released by
        owner-side GC — `ray_tpu memory --leak-suspects` keys on this.
        Unknown/placeholder owners count as alive (no false alarms)."""
        if not owner or owner == "hub":
            return True
        if owner == "driver":
            return self.driver_conn is not None
        if owner.startswith("client-"):
            return any(
                f"client-{seq}" == owner
                for seq, _t in self.client_conns.values()
            )
        w = self.workers.get(owner)
        return w is not None and w.conn is not None

    def _on_put(self, conn, p):
        tr = p.get("trace")
        if tr is None:
            self._object_ready(
                p["object_id"], p["kind"], p["payload"], p.get("size", 0),
                node_id=self._conn_node(conn), owner=self._conn_label(conn),
            )
            return
        t0 = time.monotonic()
        self._object_ready(
            p["object_id"], p["kind"], p["payload"], p.get("size", 0),
            node_id=self._conn_node(conn), owner=self._conn_label(conn),
        )
        self._emit_runtime_span(
            "hub.put", "put", (tr[0], tr[1]), t0, time.monotonic(),
            object_id=p["object_id"].hex(), size=p.get("size", 0),
        )

    def _object_ready(self, oid: bytes, kind: str, payload: Any, size: int,
                      node_id: str = "node0", owner: str = ""):
        e = self.objects.get(oid)
        if e is None:
            e = self.objects[oid] = ObjEntry()
        if owner and not e.owner:
            e.owner = owner
        if e.ready:
            return
        e.ready, e.kind, e.payload, e.size = True, kind, payload, size
        e.node_id = node_id
        if kind == P.VAL_SHM and size > 0:
            self._account_segment(oid, e)
        self._reconstructing.discard(oid)
        # serve fetches that were parked on reconstruction: replay the
        # ORIGINAL request payload — a chunked fetch keeps its
        # offset/length, so the reply slots into the client's
        # reassembly exactly where the pre-death chunk would have
        for wconn, req in self._reconstruct_waiters.pop(oid, []):
            self._on_fetch_object(wconn, req)
        # unblock task dependencies
        for spec in self.dep_waiters.pop(oid, []):
            spec.deps_remaining -= 1
            if spec.deps_remaining == 0:
                if spec.method is not None:
                    actor = self.actors.get(spec.actor_id)
                    if actor is None or actor.state == "dead":
                        from ..exceptions import ActorDiedError

                        blob = dumps_inline(ActorDiedError(msg="Actor is dead."))
                        for roid in spec.return_ids:
                            self._object_ready(roid, P.VAL_ERROR, blob, 0)
                        self._unpin_deps(spec)
                    else:
                        self._route_actor_call(actor, spec)
                else:
                    self._enqueue_runnable(spec)
        # fulfill GET waiters
        for req in self.obj_get_waiters.pop(oid, []):
            if req.done:
                continue
            req.remaining.discard(oid)
            if not req.remaining:
                self._fulfill_get(req)
        # readiness push: one P.READY_PUSH per subscribed conn (batched
        # into that peer's next outbox flush alongside everything else)
        self._push_ready(oid)
        # fulfill WAIT waiters (registration is per-occurrence, so a req
        # appearing k times in the list gets k increments — consistent
        # with duplicate ids in the original request)
        for req in self.obj_wait_waiters.pop(oid, []):
            if req.done:
                continue
            req.n_ready += 1
            if req.n_ready >= req.num_returns:
                self._fulfill_wait(req)
        # ownership GC: the owner released this ref before the value
        # arrived — nothing can fetch it, free right away (unless an
        # in-flight task pinned it as an arg)
        if self._released_early.pop(oid, None):
            if e.pins > 0:
                e.release_pending = True
            else:
                self._free_ids([oid])
        self._dispatch()

    # ---- shm budget: LRU accounting + disk spill (reference: plasma
    # eviction_policy.h + _private/external_storage.py:72 filesystem spill)
    def _account_segment(self, oid: bytes, e: ObjEntry):
        node = self.nodes.get(e.node_id)
        if node is None:
            return
        lru = self._lru.setdefault(e.node_id, __import__("collections").OrderedDict())
        if oid not in lru:
            node.store_used += e.size
        lru[oid] = e.size
        lru.move_to_end(oid)
        self._maybe_spill(node)
        self._bm_store_gauge(node)

    def _touch_segment(self, oid: bytes, e: ObjEntry):
        lru = self._lru.get(e.node_id)
        if lru is not None and oid in lru:
            lru.move_to_end(oid)

    def _drop_segment_accounting(self, oid: bytes, e: ObjEntry):
        lru = self._lru.get(e.node_id)
        if lru is not None:
            size = lru.pop(oid, None)
            if size is not None:
                node = self.nodes.get(e.node_id)
                if node is not None:
                    node.store_used = max(0.0, node.store_used - size)
                    self._bm_store_gauge(node)

    def _maybe_spill(self, node: NodeEntry):
        if node.store_cap <= 0 or node.store_used <= node.store_cap:
            return
        lru = self._lru.get(node.node_id)
        if not lru:
            return
        # oldest-first until under the cap; never spill the newest entry
        # (it may be the object being created right now)
        victims = []
        for oid in list(lru.keys())[:-1]:
            if node.store_used <= node.store_cap:
                break
            size = lru.pop(oid)
            node.store_used = max(0.0, node.store_used - size)
            victims.append(oid)
        for oid in victims:
            e = self.objects.get(oid)
            if e is None or e.spilled:
                continue
            e.spilled = True
            self._bm_spills["value"] += 1
            self._record_event(
                "spill", object_id=oid.hex(), size=e.size,
                node_id=node.node_id,
            )
            if node.agent_conn is None:
                os.makedirs(self.spill_dir, exist_ok=True)
                src = os.path.join(node.session_dir, "objects", e.payload)
                try:
                    import shutil as _sh

                    # shutil.move: tmpfs -> disk crosses filesystems, where
                    # os.replace would raise EXDEV
                    _sh.move(src, os.path.join(self.spill_dir, e.payload))
                except OSError as err:
                    sys.stderr.write(f"[ray_tpu] spill failed: {err}\n")
                    e.spilled = False
            else:
                self._send(node.agent_conn, "obj_spill", {"name": e.payload})

    def _fulfill_get(self, req: GetReq):
        req.done = True
        self._inflight_reqs.pop((id(req.conn), req.req_id), None)
        values = []
        for oid in req.all_ids:
            e = self.objects[oid]
            if e.kind == P.VAL_SHM:
                self._touch_segment(oid, e)
            values.append((oid, e.kind, e.payload))
        self._reply(req.conn, req.req_id, values=values)

    def _on_get(self, conn, p):
        tr = p.get("trace")
        if tr is None or (id(conn), p["req_id"]) in self._inflight_reqs:
            # untraced, or a ~2s retransmit of a still-parked request:
            # one hub.get span per logical get, not one per resend (a
            # get parked on a 60s task would otherwise burn ~30 spans
            # of the trace's cap)
            return self._handle_get(conn, p)
        # handler time only — a parked GET's wait belongs to the
        # producing task's stages, not to this span
        t0 = time.monotonic()
        try:
            return self._handle_get(conn, p)
        finally:
            self._emit_runtime_span(
                "hub.get", "get", (tr[0], tr[1]), t0, time.monotonic(),
                n=len(p.get("object_ids", ())),
            )

    def _handle_get(self, conn, p):
        key = (id(conn), p["req_id"])
        if key in self._inflight_reqs:
            return  # retransmit of a still-parked request; reply will come
        ids = p["object_ids"]
        missing = {oid for oid in ids if not self.objects.get(oid, ObjEntry()).ready}
        req = GetReq(conn=conn, req_id=p["req_id"], remaining=missing, all_ids=ids)
        if not missing:
            self._fulfill_get(req)
            return
        self._inflight_reqs[key] = req
        for oid in missing:
            if oid not in self.objects:
                self.objects[oid] = ObjEntry()
            self.obj_get_waiters.setdefault(oid, []).append(req)
        timeout = p.get("timeout")
        if timeout is not None:
            def expire(req=req):
                if not req.done:
                    req.done = True
                    self._inflight_reqs.pop((id(req.conn), req.req_id), None)
                    self._unregister_get_waiter(req)
                    self._reply(req.conn, req.req_id, timeout=True)
            self._add_timer(timeout, expire)

    def _unregister_get_waiter(self, req: GetReq):
        """Expired GETs must leave the per-object waiter lists, or
        requests on never-created objects accumulate forever (r1 Weak
        finding: hub waiter leak)."""
        for oid in req.remaining:
            lst = self.obj_get_waiters.get(oid)
            if lst is not None:
                try:
                    lst.remove(req)
                except ValueError:
                    pass
                if not lst:
                    del self.obj_get_waiters[oid]

    def _unregister_wait_waiter(self, req: WaitReq):
        for oid in req.ids:
            lst = self.obj_wait_waiters.get(oid)
            if lst is not None:
                try:
                    lst.remove(req)
                except ValueError:
                    pass
                if not lst:
                    del self.obj_wait_waiters[oid]

    def _fulfill_wait(self, req: WaitReq, expired: bool = False):
        """One final O(n) pass to build the reply; all intermediate
        progress was tracked incrementally in req.n_ready."""
        ready_all = []
        for oid in req.ids:
            e = self.objects.get(oid)
            if e is not None and e.ready:
                ready_all.append(oid)
        if not expired and len(ready_all) < req.num_returns:
            # a counted-ready object reverted (freed, or un-readied by
            # node-loss reconstruction) after the initial scan; rebuild
            # the incremental state and keep waiting (rare path)
            self._unregister_wait_waiter(req)
            req.n_ready = len(ready_all)
            for oid in req.ids:
                if oid not in self.objects:
                    self.objects[oid] = ObjEntry()
                if not self.objects[oid].ready:
                    self.obj_wait_waiters.setdefault(oid, []).append(req)
            return
        req.done = True
        self._inflight_reqs.pop((id(req.conn), req.req_id), None)
        self._unregister_wait_waiter(req)
        ready = ready_all[: req.num_returns]
        rset = set(ready)
        self._reply(
            req.conn,
            req.req_id,
            ready=ready,
            not_ready=[o for o in req.ids if o not in rset],
            # readiness beyond the quota: the client caches these so a
            # wait() pop-loop drains locally instead of round-tripping
            # per ref (the reference serves the same case from the core
            # worker's local memory store)
            also_ready=ready_all[req.num_returns:],
        )

    def _on_wait(self, conn, p):
        key = (id(conn), p["req_id"])
        if key in self._inflight_reqs:
            return  # retransmit of a still-parked request; reply will come
        ids = p["object_ids"]
        req = WaitReq(
            conn=conn,
            req_id=p["req_id"],
            ids=ids,
            num_returns=min(p["num_returns"], len(ids)),
        )
        for oid in ids:
            e = self.objects.get(oid)
            if e is not None and e.ready:
                req.n_ready += 1
        if req.n_ready >= req.num_returns:
            self._fulfill_wait(req)
            return
        self._inflight_reqs[key] = req
        for oid in ids:
            if oid not in self.objects:
                self.objects[oid] = ObjEntry()
            if not self.objects[oid].ready:
                self.obj_wait_waiters.setdefault(oid, []).append(req)
        timeout = p.get("timeout")
        if timeout is not None:
            def expire(req=req):
                if not req.done:
                    self._fulfill_wait(req, expired=True)
            self._add_timer(timeout, expire)

    def _on_release_owned(self, conn, p):
        """Ownership GC: the owner's last local handle died with the ref
        never pickled, so no other holder can exist. Free immediately if
        the value is ready; otherwise remember and free on arrival
        (the producing task may still be running)."""
        for oid in p["object_ids"]:
            e = self.objects.get(oid)
            if e is None or not e.ready:
                self._released_early[oid] = True
                while len(self._released_early) > 100_000:
                    self._released_early.pop(
                        next(iter(self._released_early))
                    )
                continue
            if e.pins > 0:
                # in-flight task (or live actor) still depends on this
                # object: defer the free to the last unpin
                e.release_pending = True
                continue
            if (
                self.obj_get_waiters.get(oid)
                or self.obj_wait_waiters.get(oid)
                or self.dep_waiters.get(oid)
            ):
                continue  # defensive: someone is mid-get; keep it
            self._free_ids([oid])

    def _unpin_deps(self, spec: Optional[TaskSpec]):
        """Drop a finalized task's dependency pins; free objects whose
        owner already released them. Idempotent (pinned_deps is
        consumed) so overlapping finalization paths are safe."""
        if spec is None or not spec.pinned_deps:
            return
        deps, spec.pinned_deps = spec.pinned_deps, []
        self._unpin_ids(deps)

    def _unpin_ids(self, ids: List[bytes]):
        for oid in ids:
            e = self.objects.get(oid)
            if e is None:
                continue
            e.pins -= 1
            if e.pins <= 0 and e.release_pending and e.ready:
                self._free_ids([oid])

    def _on_free(self, conn, p):
        self._free_ids(p["object_ids"])

    def _free_ids(self, object_ids):
        freed_shm = []
        for oid in object_ids:
            e = self.objects.pop(oid, None)
            self._drop_ready_watch(oid)
            if e and e.kind == P.VAL_SHM:
                freed_shm.append(oid)
                self._drop_segment_accounting(oid, e)
                # unlink on EVERY node: cross-node fetches install copies
                # under the same segment name on consumer hosts
                for path in (
                    os.path.join(self.session_dir, "objects", e.payload),
                    os.path.join(self.spill_dir, e.payload),
                ):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                for node in self.nodes.values():
                    if node.alive and node.agent_conn is not None:
                        self._send(node.agent_conn, P.OBJ_UNLINK,
                                   {"name": e.payload})
        # clients cache wait()-readiness locally (_known_ready); shm
        # frees invalidate those entries so a freed object stops
        # reporting ready. Inline frees are deliberately not broadcast —
        # they dominate free traffic (every small task return) and their
        # values are usually already cached client-side.
        if freed_shm and self.subscribers.get("__obj_freed__"):
            self._publish("__obj_freed__", freed_shm)

    # ----- out-of-band object plane: ownership/location directory
    def _on_resolve_object(self, conn, p):
        """Where does an object live? Returns the owner node's (or, if
        the owner died, a replica's) segment name, object-agent
        endpoint, and local file path so the consumer can move the
        bytes WITHOUT the hub (object_agent.py). Clients cache the
        reply; __obj_freed__ / __node_down__ pubsub invalidate it.
        A {node_id} query (no object_id) resolves just that node's
        endpoint — used by client-mode direct puts to find the head."""
        oid = p.get("object_id")
        if oid is None:
            node = self.nodes.get(p.get("node_id", ""))
            self._reply(conn, p["req_id"],
                        endpoint=node.object_endpoint if node else "")
            return
        e = self.objects.get(oid)
        if e is None or not e.ready or e.kind != P.VAL_SHM:
            self._reply(conn, p["req_id"], error="no such segment")
            return
        node = self.nodes.get(e.node_id)
        if node is None or not node.alive:
            node = None
            for nid in sorted(e.replicas or ()):
                cand = self.nodes.get(nid)
                if cand is not None and cand.alive:
                    node = cand
                    break
            if node is None:
                # owner and every replica are gone: the relay path owns
                # reconstruction (_on_fetch_object lineage rerun)
                self._reply(conn, p["req_id"], error="object location lost")
                return
        payload = {
            "name": e.payload,
            "node_id": node.node_id,
            "endpoint": node.object_endpoint,
            "hostname": node.hostname,
            "path": os.path.join(node.session_dir, "objects", e.payload),
            # spilled objects stay on the relay: the hub's fetch path
            # owns the restore-under-accounting step (and a same-node
            # consumer must not quietly duplicate a spilled segment
            # outside the store cap's books)
            "spilled": e.spilled,
        }
        self._reply(conn, p["req_id"], **payload)

    def _on_replica_added(self, conn, p):
        """A direct fetch installed a copy on the sender's node: record
        it so resolution can fail over if the owner dies. Replica sets
        die with their ObjEntry (free/GC) — no separate pruning."""
        e = self.objects.get(p.get("object_id"))
        node_id = p.get("node_id")
        if e is None or not e.ready or e.kind != P.VAL_SHM or not node_id:
            return
        if node_id != e.node_id:
            if e.replicas is None:
                e.replicas = set()
            e.replicas.add(node_id)

    # ----- readiness push (SUBSCRIBE_READY -> READY_PUSH)
    def _on_subscribe_ready(self, conn, p):
        """Register the connection for a readiness push on each not-yet
        -ready id; reply with the subset that is already ready. The
        push fires from _object_ready, so a wait() pop-loop costs one
        subscription instead of a round trip per poll."""
        ready = []
        watched = self._ready_watch_conns.setdefault(id(conn), set())
        for oid in p["object_ids"]:
            e = self.objects.get(oid)
            if e is not None and e.ready:
                ready.append(oid)
                continue
            if e is None:
                self.objects[oid] = ObjEntry()
            watchers = self._ready_watchers.setdefault(oid, [])
            if conn not in watchers:
                watchers.append(conn)
                watched.add(oid)
        if not watched:
            self._ready_watch_conns.pop(id(conn), None)
        self._reply(conn, p["req_id"], ready=ready)

    def _push_ready(self, oid: bytes) -> None:
        watchers = self._ready_watchers.pop(oid, None)
        if not watchers:
            return
        if self._traced_oids:
            tr = self._traced_oids.pop(oid, None)
            if tr is not None:
                # near-instant marker: when the hub told the waiting
                # client its traced result was ready (readiness push)
                now = time.monotonic()
                self._emit_runtime_span(
                    "hub.ready_push", "ready_push", tr, now, now,
                    object_id=oid.hex(), n_watchers=len(watchers),
                )
        for conn in watchers:
            self._send(conn, P.READY_PUSH, {"ready": [oid]})
            watched = self._ready_watch_conns.get(id(conn))
            if watched is not None:
                watched.discard(oid)
                if not watched:
                    self._ready_watch_conns.pop(id(conn), None)

    def _drop_ready_watch(self, oid: bytes) -> None:
        """Forget watchers of a freed id (no push: the object will
        never become ready; waiters re-sync on their retry period)."""
        for conn in self._ready_watchers.pop(oid, ()):
            watched = self._ready_watch_conns.get(id(conn))
            if watched is not None:
                watched.discard(oid)
                if not watched:
                    self._ready_watch_conns.pop(id(conn), None)

    def _on_fetch_object(self, conn, p):
        """Cross-node shm fetch: the consumer's local store misses, so the
        bytes are pulled from the producer node through the control plane
        (the reference's object manager push/pull, simplified: metadata
        and transfer share the hub connection — fine for control-plane
        sizes; TPU bulk tensors ride ICI collectives, not the store)."""
        if p.get("fallback"):
            # first relay chunk of a failed direct transfer: record it
            # (once per transfer — only offset 0 carries the flag)
            self._record_fallback(p["object_id"], p["fallback"], "fetch")
        oid = p["object_id"]
        if oid in self._reconstructing:
            # a fetch racing an in-flight lineage rerun (the backoff
            # retransmit of the very request that triggered it, or a
            # second consumer): the entry is marked not-ready for the
            # whole reconstruction window, so falling through to the
            # "no such segment" reply would turn a recoverable wait
            # into ObjectLostError at the client. Park it beside the
            # fetch that started the rerun (idempotent per req_id).
            waiters = self._reconstruct_waiters.setdefault(oid, [])
            if not any(
                w[0] is conn and w[1]["req_id"] == p["req_id"]
                for w in waiters
            ):
                waiters.append((conn, self._park_fetch_payload(p)))
                # same give-up bound as the kick-off fetch: a rerun
                # that never completes must fail these waiters too
                self._add_timer(60.0, lambda oid=oid: self._reconstruct_give_up(oid))
            return
        e = self.objects.get(oid)
        if e is None or not e.ready or e.kind != P.VAL_SHM:
            self._reply(conn, p["req_id"], data=None, error="no such segment")
            return
        node = self.nodes.get(e.node_id)
        if node is None or not node.alive:
            # primary copy died with its node: reconstruct by re-running
            # the producing task (reference: ObjectRecoveryManager)
            spec = self._lineage.get(p["object_id"])
            if spec is not None:
                self._reconstruct_waiters.setdefault(oid, []).append(
                    (conn, self._park_fetch_payload(p))
                )
                self._add_timer(
                    60.0, lambda oid=oid: self._reconstruct_give_up(oid)
                )
                if p["object_id"] not in self._reconstructing:
                    self._reconstructing.update(spec.return_ids)
                    for roid in spec.return_ids:
                        entry = self.objects.get(roid)
                        if entry is not None:
                            self._drop_segment_accounting(roid, entry)
                            entry.ready = False
                            entry.spilled = False
                    spec.retries_left = max(spec.retries_left, 1)
                    spec.options.pop("_pool", None)
                    self.tasks[spec.task_id] = spec
                    self._enqueue_runnable(spec)
                return
            self._reply(conn, p["req_id"], data=None,
                        error=f"object lost: node {e.node_id} is gone")
            return
        same_node = self._conn_node(conn) == e.node_id
        if e.spilled and same_node:
            # the consumer will reinstall the segment into this node's
            # shm anyway — restore it under accounting (possibly spilling
            # colder objects) so the cap stays authoritative
            if node.agent_conn is None:
                try:
                    import shutil as _sh

                    _sh.move(
                        os.path.join(self.spill_dir, e.payload),
                        os.path.join(node.session_dir, "objects", e.payload),
                    )
                    e.spilled = False
                except OSError:
                    pass
            else:
                self._send(node.agent_conn, P.OBJ_RESTORE, {"name": e.payload})
                e.spilled = False
            if not e.spilled:
                self._bm_restores["value"] += 1
                self._account_segment(p["object_id"], e)
        offset = p.get("offset")
        length = p.get("length")
        if node.agent_conn is None:
            path = os.path.join(
                self.spill_dir if e.spilled else
                os.path.join(node.session_dir, "objects"),
                e.payload,
            )
            try:
                with open(path, "rb") as f:
                    if offset is None:
                        data, total = f.read(), None
                    else:
                        # chunked streaming for shm-less clients
                        # (reference: dataservicer.py chunked GetObject)
                        total = os.fstat(f.fileno()).st_size
                        f.seek(offset)
                        data = f.read(length)
            except OSError as err:
                self._reply(conn, p["req_id"], data=None, error=str(err))
                return
            self._reply(conn, p["req_id"], data=data, total=total)
            return
        fid = next(self._fetch_seq)
        self._pending_fetches[fid] = (
            conn, self._park_fetch_payload(p), node.node_id
        )
        self._send(node.agent_conn, P.OBJ_READ,
                   {"fetch_id": fid, "name": e.payload,
                    "offset": offset, "length": length})

    def _on_obj_read_reply(self, conn, p):
        waiter = self._pending_fetches.pop(p["fetch_id"], None)
        if waiter is None:
            return
        self._reply(waiter[0], waiter[1]["req_id"], data=p.get("data"),
                    error=p.get("error"), total=p.get("total"))

    # ----- chunked client puts (shm-less client -> head-node store;
    # reference: util/client/server/dataservicer.py PutObject chunking)
    def _on_put_chunk(self, conn, p):
        e = self.objects.get(p["object_id"])
        if e is not None and e.ready:
            # replayed chunk after the stream completed (retransmit of
            # a lost-reply tail): the first `last` already sealed the
            # segment synchronously, so anything arriving now must not
            # reopen the stream or clobber the installed file
            return
        if p.get("fallback"):
            self._record_fallback(p["object_id"], p["fallback"], "put")
        name = p["name"]
        key = (id(conn), name)
        objdir = os.path.join(self.session_dir, "objects")
        tmp = os.path.join(objdir, f".client.{key[0]:x}.{name}")
        st = self._client_puts.get(key)
        try:
            if st is None:
                os.makedirs(objdir, exist_ok=True)
                st = self._client_puts[key] = open(tmp, "wb")
            if isinstance(st, tuple):  # stream already failed
                raise OSError(st[1])
            # explicit offset makes replays idempotent: a retransmitted
            # chunk seeks back and rewrites the same bytes instead of
            # appending them again (and the final size below is
            # tell() = offset+len of the true last chunk, so offset
            # accounting can't double-advance either)
            if p.get("offset") is not None:
                st.seek(p["offset"])
            st.write(p["data"])
        except OSError as err:
            # poison the stream: later chunks are dropped and the LAST
            # chunk publishes an error object so the producer's
            # follow-up get/consume surfaces the failure instead of a
            # truncated segment
            if not isinstance(st, tuple):
                try:
                    if st is not None:
                        st.close()
                    os.unlink(tmp)
                except OSError:
                    pass
            self._client_puts[key] = ("failed", str(err))
            if p.get("last"):
                self._client_puts.pop(key, None)
                self._object_ready(
                    p["object_id"], P.VAL_ERROR,
                    dumps_inline(OSError(
                        f"client put of {name} failed hub-side: {err}"
                    )), 0,
                )
            return
        if p.get("last"):
            self._client_puts.pop(key, None)
            size = st.tell()
            st.close()
            os.replace(tmp, os.path.join(objdir, name))
            self._object_ready(
                p["object_id"], P.VAL_SHM, name, size, node_id="node0"
            )

    @staticmethod
    def _park_fetch_payload(p: dict) -> dict:
        """The request payload to replay after reconstruction: keep
        req_id/offset/length (chunk identity), drop the fallback flag —
        the original delivery already recorded the transfer fallback."""
        req = dict(p)
        req.pop("fallback", None)
        return req

    def _reconstruct_give_up(self, oid: bytes) -> None:
        """Reconstruction watchdog: a rerun left unplaceable (resources
        gone) or stuck past the 60s budget fails its parked fetches
        instead of hanging them forever."""
        for wconn, req in self._reconstruct_waiters.pop(oid, []):
            self._reply(wconn, req["req_id"], data=None,
                        error="object lost: reconstruction timed out")
        self._reconstructing.discard(oid)

    def _fail_fetches_for_node(self, node_id: str):
        """Relay fetches in flight to a node that just died: replay each
        one through _on_fetch_object, which now sees the dead node and
        either parks it on a lineage rerun (reconstruction) or fails it
        with an explicit error — never a silent hang (clients wait with
        timeout=None)."""
        stale = [fid for fid, w in self._pending_fetches.items() if w[2] == node_id]
        for fid in stale:
            conn, req, _ = self._pending_fetches.pop(fid)
            if req["object_id"] in self._lineage:
                self._on_fetch_object(conn, req)
            else:
                self._reply(conn, req["req_id"], data=None,
                            error=f"object lost: node {node_id} died mid-fetch")

    # ----- streaming generators
    def _stream(self, task_id: bytes) -> StreamEntry:
        s = self.streams.get(task_id)
        if s is None:
            s = self.streams[task_id] = StreamEntry()
        return s

    def _on_stream_yield(self, conn, p):
        s = self._stream(p["task_id"])
        idx = len(s.oids)
        self._object_ready(
            p["object_id"], p["kind"], p["payload"], p.get("size", 0),
            node_id=self._conn_node(conn),
        )
        s.oids.append(p["object_id"])
        for wconn, req_id in s.next_waiters.pop(idx, []):
            s.consumed = max(s.consumed, idx + 1)
            self._reply(wconn, req_id, object_id=p["object_id"])
        self._wake_credit_waiters(s)

    def _on_stream_end(self, conn, p):
        s = self._stream(p["task_id"])
        if p.get("error") is not None:
            self._task_event(p["task_id"], state="FAILED",
                             finished_at=time.time(),
                             t_finished=time.monotonic())
            self._record_event(
                "stream_failure", task_id=p["task_id"].hex(),
                yielded=len(s.oids),
            )
            # the N+1-th ref carries the error (reference semantics)
            from .ids import ObjectID

            err_oid = ObjectID.generate().binary()
            self._object_ready(err_oid, P.VAL_ERROR, p["error"], 0)
            idx = len(s.oids)
            s.oids.append(err_oid)
            for wconn, req_id in s.next_waiters.pop(idx, []):
                self._reply(wconn, req_id, object_id=err_oid)
        s.ended = True
        for idx, waiters in list(s.next_waiters.items()):
            if idx >= len(s.oids):
                for wconn, req_id in waiters:
                    self._reply(wconn, req_id, end=True)
                del s.next_waiters[idx]
        # release any backpressured producer (it is done anyway)
        self._wake_credit_waiters(s, force=True)

    def _end_stream_with_error(self, task_id: bytes, err_blob) -> None:
        # _stream (not .get): a task failing before its first yield AND
        # before the consumer's first next() must still leave an ended
        # stream, or that first next() parks forever
        s = self._stream(task_id)
        if s.ended:
            return
        self._on_stream_end(None, {"task_id": task_id, "error": err_blob})

    def _on_stream_next(self, conn, p):
        s = self._stream(p["task_id"])
        idx = p["index"]
        if idx < len(s.oids):
            s.consumed = max(s.consumed, idx + 1)
            self._reply(conn, p["req_id"], object_id=s.oids[idx])
            self._wake_credit_waiters(s)
        elif s.ended:
            self._reply(conn, p["req_id"], end=True)
            # consumer reached the end: drop the payload index (objects
            # have their own lifecycle) and cap retained tombstones so
            # the registry cannot grow without bound
            if s.oids:
                s.oids = []
                self._ended_streams.append(p["task_id"])
                while len(self._ended_streams) > 10000:
                    old = self._ended_streams.popleft()
                    self.streams.pop(old, None)
        else:
            s.next_waiters.setdefault(idx, []).append((conn, p["req_id"]))

    def _on_stream_credit(self, conn, p):
        s = self._stream(p["task_id"])
        if s.consumed >= p["min_consumed"] or s.ended:
            self._reply(conn, p["req_id"], ok=True)
        else:
            self._bm_credit_stalls["value"] += 1
            s.credit_waiters.append((p["min_consumed"], conn, p["req_id"]))

    def _wake_credit_waiters(self, s: StreamEntry, force: bool = False):
        still = []
        for min_consumed, conn, req_id in s.credit_waiters:
            if force or s.consumed >= min_consumed:
                self._reply(conn, req_id, ok=True)
            else:
                still.append((min_consumed, conn, req_id))
        s.credit_waiters = still

    # ----- tracing spans (reference: ray.util.tracing + the task-event
    # pipeline; here one store serves the timeline AND the per-trace
    # critical-path queries)
    def _on_span_record(self, conn, p):
        """Finished tracing span from any process (util/tracing.py)."""
        self._record_span(p)

    def _record_span(self, rec: dict) -> None:
        self.spans.append(rec)
        tid = rec.get("trace_id")
        if not tid:
            return
        idx = self._trace_index
        summaries = self._trace_summaries
        lst = idx.get(tid)
        if lst is None:
            lst = idx[tid] = []
            summaries[tid] = {
                "trace_id": tid, "n_spans": 0,
                "start": rec["start"], "end": rec["end"],
                "root": rec.get("name", ""), "rooted": False,
                "procs": set(),
            }
            while len(idx) > self._trace_max:  # FIFO: oldest trace out
                old = next(iter(idx))
                idx.pop(old)
                summaries.pop(old, None)
        if len(lst) < self._trace_span_max:
            lst.append(rec)
            summ = summaries.get(tid)
            if summ is not None:
                summ["n_spans"] += 1
                if rec["start"] < summ["start"]:
                    summ["start"] = rec["start"]
                if rec["end"] > summ["end"]:
                    summ["end"] = rec["end"]
                if rec.get("parent_id") is None and not summ["rooted"]:
                    # the first parentless span is the trace root; until
                    # one arrives the first span's name stands in
                    summ["root"] = rec.get("name", "")
                    summ["rooted"] = True
                summ["procs"].add((rec.get("node_id"), rec.get("pid")))

    def _emit_runtime_span(self, name: str, stage: str, trace: tuple,
                           t0: float, t1: float,
                           parent: Optional[str] = None,
                           **attrs) -> str:
        """Record one hub-side runtime span (state-plane thread only —
        in sharded mode shards funnel their measurements through the
        ring instead of calling this, GL010). Returns the span id so a
        caller can parent further spans under it."""
        rec = self._make_runtime_record(
            name, stage, trace[0],
            parent if parent is not None else trace[1],
            t0, t1, node_id="node0", **attrs,
        )
        self._record_span(rec)
        return rec["span_id"]

    def _on_metric_record(self, conn, p):
        key = (p["name"], p["tags"])
        m = self.metrics.get(key)
        if m is None:
            # cardinality is bounded by distinct (name, tags) series —
            # a scrape registry, not a per-request table
            m = self.metrics[key] = {  # graftlint: disable=GL009
                "name": p["name"],
                "type": p["type"],
                "description": p.get("description", ""),
                "tags": p["tags"],
                "value": 0.0,
                "sum": 0.0,
                "count": 0,
                # defensively re-sort: first-match bucketing below is
                # only correct on ascending boundaries (the Histogram
                # constructor validates, but raw senders bypass it)
                "buckets": [[b, 0] for b in sorted(p.get("boundaries", ()))],
            }
        elif m["type"] != p["type"]:
            # first-wins: the record still lands in the original entry
            # (unchanged semantics), but the conflict is no longer
            # silent — one flight-recorder event per (name, tags) key
            if not m.get("type_conflict"):
                m["type_conflict"] = True
                self._record_event(
                    "metric_type_conflict", name=p["name"],
                    registered=m["type"], attempted=p["type"],
                )
        op = p["op"]
        if op == "add":
            m["value"] += p["value"]
        elif op == "set":
            m["value"] = p["value"]
        elif op == "observe":
            m["sum"] += p["value"]
            m["count"] += 1
            for pair in m["buckets"]:
                if p["value"] <= pair[0]:
                    pair[1] += 1
                    break

    # ----- sampling profiler ingest (profiling.py): every process's
    # sampler folds locally and flushes PROFILE_BATCH once a flush
    # period; the hub is the aggregation point list_state("profile")
    # and `ray_tpu profile` read from.
    def _drain_profile_inbox(self) -> None:
        # hub's own sampler hands batches over via the SPSC inbox
        # (sampler thread appends, this thread drains) — same
        # discipline as the shard rings
        while True:
            try:
                batch = self._profile_inbox.popleft()
            except IndexError:
                break
            self._on_profile_batch(None, batch)
        if self._profiler is not None:
            self._add_timer(
                self._profiler.flush_period, self._drain_profile_inbox
            )

    def _on_profile_batch(self, conn, p):
        pid = p.get("pid")
        kind = p.get("kind") or "?"
        samples = p.get("samples") or {}
        cap = int(self.config.get("profile_store_max", 4096) or 4096)
        for key, n in samples.items():
            if not (isinstance(key, tuple) and len(key) == 4):
                continue
            skey = (pid, kind) + key
            if skey in self.profile_samples:
                self.profile_samples[skey] += n
            elif len(self.profile_samples) < cap:
                # bounded by profile_store_max with drops counter below
                self.profile_samples[skey] = n  # graftlint: disable=GL009
            else:
                # cap reached: count what we shed so the CLI can say
                # "N samples dropped" instead of silently under-reporting
                self._profile_drops += n
        while len(self.profile_procs) >= 256 and pid not in self.profile_procs:
            self.profile_procs.pop(next(iter(self.profile_procs)))
        self.profile_procs[pid] = {
            "kind": kind,
            "overhead": float(p.get("overhead") or 0.0),
            "hz": float(p.get("hz") or 0.0),
            "last_t": time.monotonic(),
        }
        self._bm(
            "ray_tpu_profiler_overhead_ratio", "gauge",
            "sampling profiler self-overhead (sample-pass time / wall)",
            (("pid", str(pid)),),
        )["value"] = float(p.get("overhead") or 0.0)

    # ----- remote stack dumps (`ray_tpu stack`): works with the
    # profiler OFF — the hub dumps its own threads inline; a worker
    # dump parks the request on a token and forwards STACK_DUMP, whose
    # STACK_REPLY is matched back here (timer-expired, bounded).
    def _on_stack_request(self, conn, p):
        target = str(p.get("target") or "hub")
        req_id = p.get("req_id")
        if target in ("hub", "head") or target == str(os.getpid()):
            from . import profiling as _profiling

            self._reply(
                conn, req_id, target="hub", pid=os.getpid(),
                threads=_profiling.dump_threads(),
            )
            return
        w = None
        for wid, entry in self.workers.items():
            if wid == target or wid.startswith(target):
                w = entry
                break
        if w is None and target.isdigit():
            for entry in self.workers.values():
                if entry.pid == int(target):
                    w = entry
                    break
        if w is None or w.conn is None:
            self._reply(
                conn, req_id, target=target, threads=[],
                error=f"no live worker matches {target!r}",
            )
            return
        if len(self._stack_waiters) >= 256:
            tok0 = next(iter(self._stack_waiters))
            self._stack_timeout(tok0)
        token = next(self._stack_token)
        self._stack_waiters[token] = (  # graftlint: disable=GL009
            conn, req_id, w.worker_id, w.pid,
        )
        self._send(w.conn, P.STACK_DUMP, {"token": token})
        self._add_timer(5.0, lambda t=token: self._stack_timeout(t))

    def _stack_timeout(self, token: int) -> None:
        waiter = self._stack_waiters.pop(token, None)
        if waiter is None:
            return
        conn, req_id, wid, _pid = waiter
        self._reply(
            conn, req_id, target=wid, threads=[],
            error=f"stack dump of {wid} timed out",
        )

    def _on_stack_reply(self, conn, p):
        waiter = self._stack_waiters.pop(p.get("token"), None)
        if waiter is None:
            return  # late reply after timeout — already answered
        rconn, req_id, wid, wpid = waiter
        self._reply(
            rconn, req_id, target=wid,
            pid=p.get("pid") or wpid,
            threads=p.get("threads") or [],
        )

    # ----- task events (reference: core_worker/task_event_buffer.h;
    # feeds list_state("tasks") + the chrome-trace timeline)
    def _task_event(self, task_id: bytes, **fields) -> dict:
        ev = self._task_event_index.get(task_id)
        if ev is None:
            ev = {"task_id": task_id.hex()}
            self._task_event_index[task_id] = ev
            self.task_events.append(ev)
            # dicts are insertion-ordered: evict oldest in O(1) per event
            # (materializing the key list here was O(n) per TASK once the
            # index filled — it halved actor-call throughput after 20k
            # lifetime tasks)
            while len(self._task_event_index) > self.task_events.maxlen:
                self._task_event_index.pop(
                    next(iter(self._task_event_index))
                )
        ev.update(fields)
        return ev

    # ----- pubsub (reference: src/ray/pubsub/publisher.h:300 — here a
    # direct push over the subscriber's persistent connection)
    def _on_subscribe(self, conn, p):
        # channel-name cardinality bounded; conns pruned on disconnect
        subs = self.subscribers.setdefault(p["channel"], [])  # graftlint: disable=GL009
        if conn not in subs:
            subs.append(conn)

    def _on_publish(self, conn, p):
        # client-published user data arrives pre-serialized as a
        # cloudpickle "blob" (client.publish) so the plain-pickle frame
        # codec never sees raw user objects; it is forwarded opaque and
        # unwrapped by the subscribing client's reader
        self._publish(p["channel"], p.get("data"), blob=p.get("blob"))

    def _publish(self, channel: str, data=None, blob=None) -> None:
        # dead conns are pruned by _handle_disconnect; _send tolerates
        # races with a closing socket
        if blob is not None:
            body = {"channel": channel, "blob": blob}
        else:
            # hub-internal publishes (__logs__, __obj_freed__) are
            # plain dicts/lists of primitives — frame-codec safe as-is
            body = {"channel": channel, "data": data}
        for sub in self.subscribers.get(channel, ()):
            self._send(sub, P.PUBSUB_MSG, body)

    def _on_log_record(self, conn, p):
        # worker stdout/stderr lines fan out to log subscribers (the
        # reference's log_monitor -> driver pattern)
        wid = self.conn_to_worker.get(conn, "?")
        self._publish("__logs__", dict(p, worker_id=wid))

    # ----- jobs (multi-tenant scheduling registry)
    def _on_register_job(self, conn, p):
        """Register a driver/job's scheduling identity: tenant id,
        priority, optional quota (fairsched). Called from
        init(job_config=...) and by submitted jobs; pruned when the
        registering connection goes away (_handle_disconnect)."""
        entry = self.fairsched.register_job(
            p.get("job_id") or f"job-{id(conn):x}",
            tenant=p.get("tenant") or "default",
            priority=self.fairsched.priority_of(p),
            quota=p.get("quota"),  # tri-state: None keeps the old cap
            conn_id=id(conn),
        )
        self._record_event(
            "job_registered", job_id=entry.job_id, tenant=entry.tenant,
            priority=entry.priority, quota=dict(entry.quota),
        )
        # a lowered quota can strand parked work that now exceeds the
        # cap outright — fail it loudly rather than wedge the queue
        cap = self.fairsched.tenants.get(entry.tenant)
        for spec in self.fairsched.pop_infeasible(entry.tenant):
            self._fail_task(spec, ValueError(
                f"task requires {spec.resources} but tenant "
                f"'{entry.tenant}' quota is now "
                f"{cap.quota if cap else {}} — it can never be admitted"
            ))
        self._refresh_pending_quota_gauge()
        self._reply(conn, p["req_id"], ok=True)
        self._dispatch()  # a quota change can unblock parked work

    # ----- functions
    def _on_register_function(self, conn, p):
        # content-addressed export table: retries and late-spawning
        # workers may fetch any registered fn for the session's life
        self.functions[p["fn_id"]] = p["blob"]  # graftlint: disable=GL009

    def _on_get_function(self, conn, p):
        self._reply(conn, p["req_id"], blob=self.functions.get(p["fn_id"]))

    # ----- kv
    def _on_kv_put(self, conn, p):
        if not p.get("overwrite", True) and p["key"] in self.kv:
            self._reply(conn, p["req_id"], ok=False)
            return
        self.kv[p["key"]] = p["value"]
        if self._kv_store is not None:
            self._kv_store.record_put(p["key"], p["value"])
        self._reply(conn, p["req_id"], ok=True)

    def _on_kv_get(self, conn, p):
        self._reply(conn, p["req_id"], value=self.kv.get(p["key"]))

    def _on_kv_del(self, conn, p):
        ok = self.kv.pop(p["key"], None) is not None
        if ok and self._kv_store is not None:
            self._kv_store.record_del(p["key"])
        self._reply(conn, p["req_id"], ok=ok)

    def _on_kv_keys(self, conn, p):
        prefix = p["prefix"]
        self._reply(conn, p["req_id"], keys=[k for k in self.kv if k.startswith(prefix)])

    # ----- tasks
    def _on_submit_task(self, conn, p):
        if p["task_id"] in self._task_event_index:
            # duplicate delivery (chaos dup / a replayed frame): the
            # task is already pending, running, or done — admitting a
            # second TaskSpec would double-run it and double-charge
            # quota. Ids are client-generated and unique, so a re-seen
            # id is always a duplicate, never a new task.
            return
        spec = TaskSpec(
            task_id=p["task_id"],
            fn_id=p["fn_id"],
            args_kind=p["args_kind"],
            args_payload=p["args_payload"],
            return_ids=p["return_ids"],
            resources=p["resources"],
            options=p["options"],
            retries_left=p["options"].get("max_retries", 3),
            owner=self._conn_label(conn),
        )
        tr = p.get("trace")
        if tr is None:
            self._admit(spec, p.get("arg_deps", []))
            return
        # sampled submit: the admit span covers dep registration, quota
        # admission, and any synchronous dispatch pass it triggers
        spec.trace = (tr[0], tr[1])
        t0 = time.monotonic()
        self._admit(spec, p.get("arg_deps", []))
        self._emit_runtime_span(
            "hub.admit", "admit", spec.trace, t0, time.monotonic(),
            task_id=spec.task_id.hex(),
        )

    def _on_submit_tasks(self, conn, p):
        """Bulk admission: N homogeneous tasks from ONE wire frame
        (client.submit_many / RemoteFunction.map). Shared fields
        (fn_id/resources/options) are hoisted into the outer payload;
        the batch is admitted in one pass — one fairsched fold over
        the deps-clear specs, one dedup-index insert per task, and a
        SINGLE scheduler wake at the end instead of N. Per-conn FIFO
        holds: tasks enter the runnable queues in list order, exactly
        as N sequential SUBMIT_TASKs would."""
        fn_id = p["fn_id"]
        resources = p["resources"]
        base_opts = p["options"]
        retries = base_opts.get("max_retries", 3)
        tr = p.get("trace")
        t0 = time.monotonic()
        owner_label = self._conn_label(conn)
        fresh: List[TaskSpec] = []
        for t in p["tasks"]:
            if t["task_id"] in self._task_event_index:
                # replayed batch (retransmit after a lost ack) or chaos
                # dup: every already-seen task is pending/running/done
                continue
            spec = TaskSpec(
                task_id=t["task_id"],
                fn_id=fn_id,
                args_kind=t["args_kind"],
                args_payload=t["args_payload"],
                return_ids=t["return_ids"],
                resources=resources,
                # per-task copy: fairsched stamps _fs_counted and the
                # scheduler mutates options in place — sharing the
                # frame's dict across specs would cross-contaminate
                options=dict(base_opts),
                retries_left=retries,
                owner=owner_label,
                # bulk pipelining is an opt-IN the explicit bulk paths
                # (map/submit_many) keep by default; auto-batched plain
                # .remote() frames splice "pipeline": False so strict
                # per-call placement semantics survive the batching
                bulk=p.get("pipeline", True),
            )
            if tr is not None:
                spec.trace = (tr[0], tr[1])
            self._admit(spec, t["arg_deps"], enqueue=False)
            if spec.deps_remaining == 0:
                fresh.append(spec)
        if fresh:
            try:
                verdicts = self.fairsched.admit_many(fresh)
            except QuotaInfeasibleError as err:
                for spec in fresh:
                    self.tasks[spec.task_id] = spec
                    self._fail_task(spec, ValueError(str(err)))
                verdicts = None
            if verdicts is not None:
                parked = False
                for spec, ok in zip(fresh, verdicts):
                    if ok:
                        self._enqueue_ready(spec, dispatch=False)
                    else:
                        self.tasks[spec.task_id] = spec
                        self._task_event(spec.task_id,
                                         state="PENDING_QUOTA")
                        parked = True
                if parked:
                    self._refresh_pending_quota_gauge()
        if tr is not None:
            # one client.submit span fans out to N hub.admit children;
            # each child gets a 1/N slice of the admission window so
            # the per-stage durations still partition wall time
            t1 = time.monotonic()
            n = max(len(p["tasks"]), 1)
            dt = (t1 - t0) / n
            for i, t in enumerate(p["tasks"]):
                self._emit_runtime_span(
                    "hub.admit", "admit", (tr[0], tr[1]),
                    t0 + i * dt, t0 + (i + 1) * dt,
                    task_id=t["task_id"].hex(),
                )
        req_id = p.get("req_id")
        if req_id is not None:
            self._reply(conn, req_id, ok=True, admitted=len(fresh))
        self._dispatch()

    def _admit(self, spec: TaskSpec, deps: List[bytes],
               enqueue: bool = True):
        pending = 0
        for dep in deps:
            e = self.objects.get(dep)
            if e is None:
                e = self.objects[dep] = ObjEntry()
            e.pins += 1
            spec.pinned_deps.append(dep)
            if not e.ready:
                pending += 1
                self.dep_waiters.setdefault(dep, []).append(spec)
        spec.deps_remaining = pending
        self.tasks[spec.task_id] = spec
        # lifecycle stamps: wall clocks (submitted_at/...) are display
        # timestamps for the timeline; the t_* monotonic twins are what
        # durations (queue wait, run time) are computed from — wall
        # deltas step with NTP (graftlint GL008 guards the distinction)
        ev = self._task_event(
            spec.task_id, name=spec.fn_id or (spec.method or ""),
            state="PENDING_ARGS" if pending else "PENDING_SCHEDULING",
            submitted_at=time.time(), t_submit=time.monotonic(),
        )
        if spec.trace is not None:
            # the trace id rides the task event so flight-recorder
            # entries (retry/fail/preempt) and the timeline cross-link
            ev["trace_id"] = spec.trace[0]
        if pending == 0 and enqueue:
            self._enqueue_runnable(spec)

    def _sched_class(self, spec: TaskSpec) -> tuple:
        pg = spec.options.get("placement_group")
        res_key = tuple(sorted(spec.resources.items()))
        # tenant and priority terminate the tuple — fairsched's class
        # ordering reads them positionally (class_order_key)
        return (res_key, pg[0] if pg else None, pg[1] if pg else None,
                spec.options.get("runtime_env_hash", ""),
                spec.options.get("tenant") or "default",
                self.fairsched.priority_of(spec.options))

    def _enqueue_runnable(self, spec: TaskSpec):
        try:
            admitted = self.fairsched.admit(spec)
        except QuotaInfeasibleError as err:
            # the request exceeds the quota outright: it could never be
            # admitted — fail loudly instead of parking forever (and
            # wedging the tenant's FIFO queue behind it)
            self.tasks[spec.task_id] = spec
            self._fail_task(spec, ValueError(str(err)))
            return
        if not admitted:
            # over-quota: parked in the tenant's pending_quota queue;
            # re-admitted by _dispatch_once as finishing work frees room
            self.tasks[spec.task_id] = spec
            self._task_event(spec.task_id, state="PENDING_QUOTA")
            self._refresh_pending_quota_gauge()
            return
        self._enqueue_ready(spec)

    def _refresh_pending_quota_gauge(self) -> None:
        self._bm_pending_quota["value"] = float(
            self.fairsched.parked_count()
        )

    def _enqueue_ready(self, spec: TaskSpec, dispatch: bool = True):
        key = self._sched_class(spec)
        q = self.runnable.get(key)
        if q is None:
            q = self.runnable[key] = deque()
        q.append(spec)
        # deps resolved: the task is now scheduler-visible (a retry
        # re-stamps, so the breakdown reflects the latest attempt)
        ev = self._task_event_index.get(spec.task_id)
        if ev is not None:
            ev["t_queued"] = time.monotonic()
        if dispatch:
            self._dispatch()

    def _resources_fit(self, need: Dict[str, float], avail: Dict[str, float]) -> bool:
        return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())

    def _acquire(self, need: Dict[str, float], avail: Dict[str, float]):
        for k, v in need.items():
            avail[k] = avail.get(k, 0.0) - v

    def _release(self, need: Dict[str, float], avail: Dict[str, float]):
        for k, v in need.items():
            avail[k] = avail.get(k, 0.0) + v

    def _effective_pools(self, spec: TaskSpec):
        """Resource pools this task draws from: node-wide, or a PG bundle."""
        pg = spec.options.get("placement_group")
        if pg:
            pg_id, bundle_idx = pg
            entry = self.pgs.get(pg_id)
            if entry is None:
                return None  # PG removed; fail the task
            if not entry.ready:
                self._try_reserve_pg(entry)
                if not entry.ready:
                    return []  # PG not reserved yet: task must queue
            if bundle_idx is not None and bundle_idx >= len(entry.bundles):
                return None  # invalid bundle index; fail the task
            if bundle_idx is None or bundle_idx < 0:
                # any bundle with room
                for i, avail in enumerate(entry.bundle_avail):
                    if self._resources_fit(spec.resources, avail):
                        return [("pg", entry, i)]
                return []
            return [("pg", entry, bundle_idx)]
        return [("node", None, None)]

    def _candidate_nodes(self, spec: TaskSpec) -> Optional[List[NodeEntry]]:
        """Nodes this task may run on (node-pool path): head-first order,
        restricted by NodeAffinitySchedulingStrategy when present.
        Returns None when a HARD affinity target is dead/unknown — the
        task must fail, not queue forever (reference:
        node_affinity_scheduling_policy fails infeasible hard affinity)."""
        affinity = spec.options.get("node_affinity")
        nodes = self._ordered_nodes()
        if affinity:
            node_id, soft = affinity
            pinned = [n for n in nodes if n.node_id == node_id]
            if pinned:
                return pinned
            if not soft:
                return None
        return nodes

    def _dispatch(self):
        # Non-reentrant: placement can fail tasks, which marks objects ready,
        # which can trigger nested _dispatch calls — those just set a flag and
        # the outer frame loops again over consistent state.
        if self._dispatching:
            self._dispatch_pending = True
            return
        self._dispatching = True
        try:
            while True:
                self._dispatch_pending = False
                self._dispatch_once()
                if not self._dispatch_pending:
                    break
        finally:
            self._dispatching = False

    def _dispatch_once(self):
        # Head-only placement per scheduling class: O(#classes) per event.
        self._spawn_wants = {}
        empty_keys = []
        # re-admit quota-parked work that now fits (finishing tasks
        # freed admitted usage since the last pass)
        unparked = self.fairsched.pop_admissible()
        if unparked:
            for spec in unparked:
                self._task_event(spec.task_id, state="PENDING_SCHEDULING")
                self._enqueue_ready(spec, dispatch=False)
            self._refresh_pending_quota_gauge()
        classes = list(self.runnable.items())
        if len(classes) > 1:
            # policy order: priority first, then the tenant furthest
            # below its weighted fair share. The sort is stable, so
            # same-priority/same-tenant classes keep insertion order —
            # and a blocked class never stops the walk: every class
            # still gets its head-of-queue placement attempt per pass
            # (no head-of-line blocking across classes).
            classes.sort(
                key=lambda kv: self.fairsched.class_order_key(kv[0])
            )
        for key, q in classes:
            while q:
                self._last_spawn_node = None
                placed = self._try_place(q[0], qlen=len(q))
                if placed in ("placed", "failed"):
                    q.popleft()
                else:
                    # the whole class is blocked; if the head wanted a
                    # worker, the rest of the queue wants one too (keeps
                    # warm-up spawning parallel, not one-per-pass). Each
                    # want carries ITS OWN spec's actor flag — the head's
                    # flag must not leak onto queued plain tasks (that
                    # would bypass the pooled-worker cap). Enumerate at
                    # most max_workers wants: spawning can never exceed
                    # the pool cap in one pass, and walking the WHOLE
                    # queue here made every dispatch event O(queue) — a
                    # 1k-task burst on a saturated pool went quadratic.
                    if self._last_spawn_node is not None and len(q) > 1:
                        nd = self.nodes.get(self._last_spawn_node)
                        cap = nd.max_workers if nd is not None else 32
                        # +64 headroom so actor gangs (uncapped by the
                        # pool) larger than max_workers still spawn in
                        # few waves; gangs beyond the bound progress
                        # wave-by-wave as spawned workers connect
                        self._spawn_wants.setdefault(
                            self._last_spawn_node, []
                        ).extend(
                            (s.options.get("runtime_env"),
                             s.options.get("runtime_env_hash", ""),
                             s.is_actor_create)
                            for s in itertools.islice(q, 1, 65 + cap)
                        )
                    break
            if not q:
                empty_keys.append(key)
        for key in empty_keys:
            if not self.runnable.get(key):
                self.runnable.pop(key, None)
        self._bm_queue_depth["value"] = float(
            sum(len(q) for q in self.runnable.values())
        )
        # spawn workers where placement deferred for lack of an idle
        # worker. max_workers caps the POOLED task-worker count; actor
        # creations always get a process (actors pin workers for life —
        # capping them would deadlock gangs larger than the pool, where
        # the reference just grows its worker pool).
        for node_id, wants in self._spawn_wants.items():
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                continue
            n_actor = sum(1 for _, _, ia in wants if ia)
            # in-flight ACTOR-purposed spawns satisfy actor wants (so a
            # boot-storm doesn't respawn every dispatch round), and
            # pooled-purposed spawns offset pooled wants — per-purpose
            # counters so pooled spawns can't starve actor wants
            actor_quota = max(0, n_actor - node.spawning_actor)
            spawning_pooled = max(0, node.spawning - node.spawning_actor)
            budget = max(
                0,
                min(
                    (len(wants) - n_actor) - spawning_pooled,
                    node.max_workers - self._node_worker_count(node_id),
                ),
            )
            for renv, renv_hash, is_actor in wants:
                if is_actor:
                    if actor_quota > 0:
                        actor_quota -= 1
                        self._spawn_worker(node, runtime_env=renv,
                                           renv_hash=renv_hash,
                                           for_actor=True)
                elif budget > 0:
                    budget -= 1
                    self._spawn_worker(node, runtime_env=renv,
                                       renv_hash=renv_hash)

    # ----- dispatch pipelining: when the pool is saturated and the
    # backlog is deep, plain tasks queue directly behind busy workers
    # (bounded depth) instead of waiting for an idle one. The worker's
    # own task queue serializes execution, its _send_done coalesces the
    # TASK_DONE replies, and the hub outbox batches the EXEC frames —
    # on a syscall-bound box this is the difference between one wire
    # round-trip per task and one per DEPTH tasks.
    _PIPE_DEPTH = 16  # head + followers a worker may hold
    # engage only under a real backlog: short queues keep strict
    # one-task-per-worker placement (no follower can strand behind a
    # slow head; latency-sensitive interactive submits are unaffected)
    _PIPE_MIN_QUEUE = 16

    def _pipeline_ok(self, spec: TaskSpec) -> bool:
        """Only plain tasks pipeline: no actors (worker becomes the
        actor), no TPU (chip assignment is per-dispatch), no streaming
        (backpressure credits assume one producer per worker), no
        execute deadline (the timer would count worker-queue wait), no
        placement group (bundle accounting is head-only). Only BULK
        submissions (RemoteFunction.map) opt in at all — the caller
        declared a throughput-oriented fan-out; individually submitted
        tasks keep strict one-task-per-worker work-stealing."""
        o = spec.options
        return (
            spec.bulk
            and not spec.is_actor_create
            and spec.actor_id is None
            and not spec.resources.get("TPU", 0)
            and not o.get("streaming")
            and not o.get("timeout_s")
            and not o.get("placement_group")
            and not self.config.task_timeout_default_s
        )

    def _find_pipeline_worker(self, spec: TaskSpec, nodes) -> Optional[WorkerEntry]:
        """Least-loaded busy worker that can take `spec` as a follower:
        same runtime env, head holding an IDENTICAL resource dict (the
        promotion in _on_task_done swaps head resources exactly), every
        assigned task pipeline-eligible, and depth headroom."""
        allowed = {n.node_id for n in nodes}
        need_env = spec.options.get("runtime_env_hash", "")
        best = None
        for w in self.workers.values():
            if (
                w.state != "busy" or not w.pipe_ok or not w.assigned
                or w.actor_id is not None
                or w.node_id not in allowed
                or w.runtime_env_hash != need_env
                or len(w.assigned) >= self._PIPE_DEPTH
                or w.assigned[0].resources != spec.resources
            ):
                continue
            if best is None or len(w.assigned) < len(best.assigned):
                best = w
        return best

    def _try_place(self, spec: TaskSpec, qlen: int = 1) -> str:
        pools = self._effective_pools(spec)
        if pools is None:
            self._fail_task(spec, ValueError("placement group was removed"))
            return "failed"
        if not pools:
            return "defer"
        kind, entry, bidx = pools[0]
        n_chips = int(spec.resources.get("TPU", 0))
        chip_pool = None
        if kind == "pg":
            node = self.nodes.get(entry.bundle_nodes[bidx])
            if node is None or not node.alive:
                return "defer"  # bundle's node is gone; waits for recovery
            avail = entry.bundle_avail[bidx]
            if not self._resources_fit(spec.resources, avail):
                return "defer"
            if entry.bundle_chips:
                # SLICE: the task runs on the bundle's reserved chips
                chip_pool = entry.bundle_chips[bidx]
            candidates = [(node, avail)]
        else:
            allowed = self._candidate_nodes(spec)
            if allowed is None:
                self._fail_task(spec, ValueError(
                    "hard NodeAffinitySchedulingStrategy target "
                    f"{spec.options.get('node_affinity')} is not alive"))
                return "failed"
            candidates = [
                (n, n.avail)
                for n in allowed
                if self._resources_fit(spec.resources, n.avail)
            ]
            if not candidates:
                # node resources exhausted (every unit held by a running
                # task): the only way forward without pipelining is to
                # wait for a TASK_DONE. Queue behind a busy worker when
                # the backlog justifies it — the follower acquires the
                # head's resources at promotion, so accounting stays
                # exact and nothing oversubscribes.
                if qlen >= self._PIPE_MIN_QUEUE and self._pipeline_ok(spec):
                    w = self._find_pipeline_worker(spec, allowed)
                    if w is not None:
                        self._send_exec(w, spec, (), pipelined=True)
                        return "placed"
                return "defer"
        for node, avail in candidates:
            worker, chips = self._find_idle_worker(
                spec, n_chips, node, chip_pool=chip_pool
            )
            if worker is None:
                continue
            self._acquire(spec.resources, avail)
            spec.options["_pool"] = (
                ("pg", entry.pg_id, bidx) if kind == "pg"
                else ("node", node.node_id, None)
            )
            if chips and worker.pinned_chips is None:
                # pin: chips leave the node's free pool for the worker's life
                node.free_tpu_chips.difference_update(chips)
                worker.pinned_chips = chips
            self._send_exec(worker, spec, chips)
            if spec.is_actor_create:
                # the actor just pinned a pool member for life; restore
                # the pool to its prior size so the next task burst
                # doesn't pay cold worker-spawn latency (reference: the
                # raylet prestarts replacement workers when actors take
                # pool members, worker_pool.cc PrestartWorkers). Every
                # claim replenishes — gating on worker warmth let a
                # burst of actor creations drain the pool to zero (each
                # replacement is fresh, so its claim replenished
                # nothing).
                # _node_worker_count already includes the WorkerEntry
                # rows of in-flight ("starting") spawns, so adding
                # node.spawning here double-counted them: a burst of k
                # claims replenished only ~k/2 workers and the NEXT task
                # burst paid the missing interpreter spawns in-band
                # (observed as a 3x-slow first wait_1k round)
                pooled = self._node_worker_count(node.node_id)
                if pooled < node.max_workers:
                    # replenish with the SAME runtime env the claimed
                    # worker served, or env-specific bursts still stall
                    self._spawn_worker(
                        node,
                        runtime_env=spec.options.get("runtime_env"),
                        renv_hash=spec.options.get("runtime_env_hash", ""),
                    )
            return "placed"
        # Resources fit somewhere but no idle worker: request one where a
        # NEW worker could actually serve the task — for TPU tasks that
        # means the node still has n free chips (chips pinned to existing
        # idle workers don't help a fresh process). SLICE bundle tasks
        # draw from the bundle's reserved chips, which live OUTSIDE the
        # node free pool — count the unpinned ones instead.
        for node, _ in candidates:
            if chip_pool is not None:
                live_pinned = {
                    c
                    for w in self.workers.values()
                    if w.node_id == node.node_id and w.pinned_chips
                    for c in w.pinned_chips
                }
                spawnable = (
                    sum(1 for c in chip_pool if c not in live_pinned)
                    >= n_chips
                )
            else:
                spawnable = len(node.free_tpu_chips) >= n_chips
            if n_chips == 0 or spawnable:
                self._spawn_wants.setdefault(node.node_id, []).append(
                    (spec.options.get("runtime_env"),
                     spec.options.get("runtime_env_hash", ""),
                     spec.is_actor_create)
                )
                self._last_spawn_node = node.node_id
                break
        return "defer"

    def _find_idle_worker(self, spec: TaskSpec, n_chips: int,
                          node: NodeEntry, chip_pool: Optional[tuple] = None):
        """Pick an idle worker ON THIS NODE; TPU tasks require chip
        affinity (a worker pinned to exactly n chips, or a fresh worker +
        n free chips on the node). With chip_pool (a SLICE bundle's
        reserved chips) the task must land on exactly those chips."""
        need_env = spec.options.get("runtime_env_hash", "")
        if n_chips > 0:
            fresh = None
            pool_set = set(chip_pool) if chip_pool is not None else None
            for w in self.workers.values():
                if (w.state != "idle" or w.node_id != node.node_id
                        or w.runtime_env_hash != need_env):
                    continue
                if w.pinned_chips is not None and len(w.pinned_chips) == n_chips:
                    if pool_set is not None and not set(w.pinned_chips) <= pool_set:
                        continue  # pinned outside this bundle's slice
                    return w, w.pinned_chips
                if w.pinned_chips is None and fresh is None:
                    fresh = w
            if pool_set is not None:
                # reserved chips are free iff no live worker pins them
                # (they never sit in node.free_tpu_chips)
                live_pinned = {
                    c
                    for w in self.workers.values()
                    if w.node_id == node.node_id and w.pinned_chips
                    for c in w.pinned_chips
                }
                open_chips = [c for c in chip_pool if c not in live_pinned]
                if fresh is not None and len(open_chips) >= n_chips:
                    return fresh, tuple(open_chips[:n_chips])
                return None, ()
            if fresh is not None and len(node.free_tpu_chips) >= n_chips:
                return fresh, tuple(sorted(node.free_tpu_chips))[:n_chips]
            return None, ()
        best = None
        for w in self.workers.values():
            if (w.state != "idle" or w.node_id != node.node_id
                    or w.runtime_env_hash != need_env):
                continue
            # prefer non-TPU-pinned workers for CPU tasks, and fn cache hits
            if spec.fn_id in w.seen_fns and w.pinned_chips is None:
                return w, ()
            if best is None or (best.pinned_chips is not None and w.pinned_chips is None):
                best = w
        return best, ()

    def _send_exec(self, worker: WorkerEntry, spec: TaskSpec,
                   chips: Tuple[int, ...], pipelined: bool = False):
        worker.state = "busy"
        if pipelined:
            # follower: queue behind the executing head. The worker
            # process drains its task queue sequentially, and its
            # _send_done batches TASK_DONEs whenever more work is
            # queued — this is what turns a deep backlog into few
            # frames instead of a wake+syscall round-trip per task.
            worker.assigned.append(spec)
        else:
            worker.current_task = spec
            worker.tpu_chips = chips
            worker.pipe_ok = self._pipeline_ok(spec)
        now_mono = time.monotonic()
        ev = self._task_event(
            spec.task_id, state="RUNNING", started_at=time.time(),
            t_scheduled=now_mono,
            worker_id=worker.worker_id, node_id=worker.node_id,
        )
        self._bm_placed["value"] += 1
        if self.fairsched.tenants:
            self.fairsched.charge_dispatch(spec)
            self._update_tenant_gauges()
        # measure from the LATEST queue entry (retries re-stamp
        # t_queued), falling back to submit — a retry of a 10s task
        # must not record a 10s "placement"
        t0 = ev.get("t_queued") or ev.get("t_submit")
        if t0 is not None:
            self._bm_observe(self._bm_placement, now_mono - t0)
        dispatch_span = None
        if spec.trace is not None:
            # the queue-wait span: admit (or the latest retry's
            # re-queue) -> this dispatch; worker-side spans parent
            # under its id so the trace reads submit -> queue -> exec
            dispatch_span = self._emit_runtime_span(
                "hub.sched", "queue_wait", spec.trace,
                t0 if t0 is not None else now_mono, now_mono,
                task_id=spec.task_id.hex(), worker_id=worker.worker_id,
            )
            if (not worker.spawn_span_done and worker.spawned_t
                    and worker.connected_t
                    and (t0 is None or worker.connected_t >= t0)):
                # this dispatch waited on the worker's process spawn:
                # charge the spawn window to the trace (once per worker)
                worker.spawn_span_done = True
                self._emit_runtime_span(
                    "hub.worker_spawn", "spawn", spec.trace,
                    worker.spawned_t, worker.connected_t,
                    parent=dispatch_span, worker_id=worker.worker_id,
                )
        fn_blob = None
        if spec.fn_id not in worker.seen_fns:
            fn_blob = self.functions.get(spec.fn_id)
            worker.seen_fns.add(spec.fn_id)
        msg = P.EXEC_ACTOR_CREATE if spec.is_actor_create else P.EXEC_TASK
        exec_payload = {
                "task_id": spec.task_id,
                "fn_id": spec.fn_id,
                "fn_blob": fn_blob,
                "args_kind": spec.args_kind,
                "args_payload": spec.args_payload,
                "return_ids": spec.return_ids,
                "tpu_chips": chips,
                "actor_id": spec.actor_id,
                "ready_id": spec.ready_id,
                "options": {
                    k: v for k, v in spec.options.items()
                    # tenant/priority/job_id ride along so NESTED
                    # submits from inside the task inherit the job's
                    # scheduling identity (quota/fairness/priority
                    # must not be escapable by fanning out subtasks)
                    if k in ("max_concurrency", "streaming",
                             "_generator_backpressure_num_objects",
                             "_restarted", "placement_group",
                             "tenant", "priority", "job_id")
                },
        }
        if dispatch_span is not None:
            # worker spans (arg fetch / execute / result store) parent
            # under the dispatch span; nested submits inherit the trace
            exec_payload["trace"] = (spec.trace[0], dispatch_span)
        self._send(worker.conn, msg, exec_payload)
        # per-task execute deadline: options(timeout_s=...) wins, else
        # the cluster-wide hung-worker watchdog default (0 = off). A
        # one-shot timer per dispatch — the default path arms nothing.
        timeout_s = spec.options.get("timeout_s") or (
            self.config.task_timeout_default_s
        )
        # pipelined specs never reach here with a deadline
        # (_pipeline_ok excludes them): a timer armed at queue-behind
        # time would count worker-queue wait against the execute budget
        if timeout_s and timeout_s > 0 and not pipelined:
            worker.exec_gen = gen = next(self._exec_seq)
            self._add_timer(
                float(timeout_s),
                lambda w=worker, s=spec, g=gen, t=float(timeout_s):
                    self._check_exec_timeout(w, s, g, t),
            )

    def _check_exec_timeout(self, worker: WorkerEntry, spec: TaskSpec,
                            gen: int, timeout_s: float) -> None:
        """The task dispatched at generation `gen` is still running on
        `worker` past its deadline: SIGKILL the worker (a hung —
        SIGSTOP'd, deadlocked, livelocked — process ignores the
        cooperative KILL and never EOFs on its own) and let the normal
        worker-death path retry the task against its crash-retry budget
        (a timeout IS a crash, unlike a preemption — the task may hang
        every time), or fail it with TaskTimeoutError once exhausted."""
        if (
            self.workers.get(worker.worker_id) is not worker
            or worker.exec_gen != gen
            or worker.current_task is not spec
            or worker.state not in ("busy", "actor")
        ):
            return  # that dispatch already finished (or was retried)
        spec.options["_timed_out"] = timeout_s
        self._record_event(
            "task_timeout", task_id=spec.task_id.hex(),
            worker_id=worker.worker_id, timeout_s=timeout_s,
            **self._trace_fields(spec),
        )
        self._force_kill_worker(worker)

    def _deliver_worker_signal(self, w: WorkerEntry, sig: str) -> None:
        """Route "kill"/"stop" to a worker's process wherever its proc
        handle lives: hub-local Popen, or its node agent via
        P.KILL_WORKER's sig field. "kill" is SIGKILL, never SIGTERM —
        a SIGSTOP'd or wedged worker queues SIGTERM forever."""
        import signal as _signal

        try:
            if w.proc is not None:
                if sig == "stop":
                    os.kill(w.proc.pid, _signal.SIGSTOP)
                else:
                    w.proc.kill()
                return
            node = self.nodes.get(w.node_id)
            if node is not None and node.agent_conn is not None:
                self._send(node.agent_conn, P.KILL_WORKER,
                           {"worker_id": w.worker_id, "sig": sig})
        except (OSError, ProcessLookupError):
            pass

    def _force_kill_worker(self, w: WorkerEntry) -> None:
        """SIGKILL the stalled target (watchdog/timeout recovery path —
        chaos worker_hang sends SIGSTOP, so only SIGKILL terminates)."""
        self._deliver_worker_signal(w, "kill")
        # drop the conn ourselves: the EOF from the kill arrives
        # eventually, but expelling now makes recovery latency the
        # timer's, not the kernel's
        if w.conn is not None:
            self._expel_conn(w.conn)

    def _worker_pythonpath(self) -> str:
        # Propagate the driver's import paths so workers can import ray_tpu
        # and user modules regardless of cwd (the reference ships PYTHONPATH
        # to workers through the runtime env / worker command line).
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        paths = [pkg_parent] + [p for p in sys.path if p]
        if os.environ.get("PYTHONPATH"):
            paths.append(os.environ["PYTHONPATH"])
        return os.pathsep.join(dict.fromkeys(paths))

    def _spawn_worker(self, node: NodeEntry, runtime_env=None,
                      renv_hash: str = "", for_actor: bool = False):
        import json as _json

        wid = WorkerID.generate().hex()
        node.spawning += 1
        self._bm_spawns["value"] += 1
        if for_actor:
            node.spawning_actor += 1
        renv_json = _json.dumps(runtime_env) if runtime_env else ""
        if node.agent_conn is not None:
            # remote host: the node agent forks the worker there
            self.workers[wid] = WorkerEntry(
                worker_id=wid, state="starting", node_id=node.node_id,
                runtime_env_hash=renv_hash, spawned_for_actor=for_actor,
                spawned_t=time.monotonic(),
            )
            env = dict(
                self.worker_env,
                RAY_TPU_HUB_ADDR=self.addr,
                RAY_TPU_WORKER_ID=wid,
                PYTHONPATH=self._worker_pythonpath(),
            )
            if renv_json:
                env["RAY_TPU_RUNTIME_ENV"] = renv_json
            self._send(
                node.agent_conn, P.SPAWN_WORKER,
                {"worker_id": wid, "env": env},
            )
            return
        env = dict(os.environ)
        env.update(self.worker_env)
        env["RAY_TPU_HUB_ADDR"] = self.addr
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_WORKER_ID"] = wid
        env["RAY_TPU_NODE_ID"] = node.node_id
        env["PYTHONPATH"] = self._worker_pythonpath()
        if renv_json:
            env["RAY_TPU_RUNTIME_ENV"] = renv_json
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_process"],
            env=env,
            cwd=os.getcwd(),
        )
        self.workers[wid] = WorkerEntry(
            worker_id=wid, proc=proc, state="starting", node_id=node.node_id,
            runtime_env_hash=renv_hash, spawned_for_actor=for_actor,
            spawned_t=time.monotonic(),
        )

    def _reap_workers(self):
        """Detect spawned workers that died before connecting (e.g. import
        failure) so the scheduler doesn't wait on them forever."""
        dead = [
            w
            for w in self.workers.values()
            if w.proc is not None and w.proc.poll() is not None and w.conn is None
        ]
        for w in dead:
            sys.stderr.write(
                f"[ray_tpu] worker {w.worker_id} exited with code {w.proc.returncode} "
                f"before connecting\n"
            )
            self._record_event(
                "worker_spawn_failed", worker_id=w.worker_id,
                node_id=w.node_id, code=w.proc.returncode,
            )
            node = self.nodes.get(w.node_id)
            if node is not None:
                node.spawning = max(0, node.spawning - 1)
                if w.spawned_for_actor:
                    node.spawning_actor = max(0, node.spawning_actor - 1)
            self.workers.pop(w.worker_id, None)
        if dead:
            self._dispatch()
        self._add_timer(self.config.worker_reap_period_s, self._reap_workers)

    _worker_rss = staticmethod(proc_rss_bytes)

    def _memory_monitor(self):
        """Kill local workers whose RSS exceeds the per-worker cap
        (reference: common/memory_monitor.h feeding the raylet's
        worker-killing policy, worker_killing_policy.cc — we use its
        newest-first ordering: the most recently started offender dies,
        preserving long-running work)."""
        cap = self.config.memory_usage_threshold
        offenders = [
            w for w in self.workers.values()
            if w.proc is not None and w.conn is not None
            and self._worker_rss(w.proc.pid) > cap
        ]
        if offenders:
            from ..exceptions import OutOfMemoryError

            victim = offenders[-1]  # newest registered
            sys.stderr.write(
                f"[ray_tpu] memory monitor: worker {victim.worker_id} rss "
                f"exceeds {cap:.0f} bytes; killing\n"
            )
            self._record_event(
                "oom_kill", worker_id=victim.worker_id,
                node_id=victim.node_id,
                rss=self._worker_rss(victim.proc.pid), cap=cap,
            )
            spec = victim.current_task
            if spec is not None:
                # OOM kills don't burn crash retries silently: fail fast
                spec.retries_left = 0
                spec.options["_oom"] = True
            self._kill_worker(victim)
        self._add_timer(self.config.memory_monitor_period_s, self._memory_monitor)

    def _on_task_done(self, conn, p):
        wid = self.conn_to_worker.get(conn)
        worker = self.workers.get(wid) if wid else None
        spec = self.tasks.pop(p["task_id"], None)
        ispec = None  # actor-call spec (lives in actor.inflight, not tasks)
        if (
            worker is not None and worker.state == "busy"
            and worker.current_task is not None
            and worker.current_task.task_id == p["task_id"]
        ):
            # identity-gated, not state-gated: a DUPLICATE task_done
            # (chaos dup / replayed frame) whose first copy already
            # freed this worker — and whose _dispatch may have put a
            # NEW task on it — must not reset the worker under that
            # task (which would double-book it and disarm its
            # exec-timeout guard)
            worker.assigned.popleft()
            if worker.assigned:
                # pipelined follower promotes to head: it takes over the
                # node resources the finished head releases just below
                # (same scheduling class ⇒ identical resource dict), so
                # the swap is exact — avail dips negative for the few
                # lines until _release_task_resources restores it, with
                # no reader in between. _pool presence is the
                # "resources acquired" marker release keys off.
                nh = worker.assigned[0]
                if "_pool" not in nh.options:
                    node = self.nodes.get(worker.node_id)
                    if node is not None:
                        self._acquire(nh.resources, node.avail)
                        nh.options["_pool"] = ("node", worker.node_id, None)
            else:
                worker.state = "idle"
                worker.tpu_chips = ()  # chips stay pinned to the worker (affinity)
        if spec is not None:
            self._release_task_resources(spec)
            if spec.actor_id is not None:
                actor = self.actors.get(spec.actor_id)
                if actor is not None:
                    actor.inflight.pop(p["task_id"], None)
        elif worker is not None and worker.actor_id:
            actor = self.actors.get(worker.actor_id)
            if actor is not None:
                ispec = actor.inflight.pop(p["task_id"], None)
        tr = None
        for s in (spec, ispec):
            if s is not None and s.trace is not None:
                tr = s.trace
                break
        node_id = worker.node_id if worker is not None else "node0"
        if self._maybe_retry_app_error(spec, p["returns"]):
            self._dispatch()
            return
        t_done0 = 0.0
        if tr is not None:
            # the returns become ready below; readiness pushes to
            # subscribed waiters stitch in through this map (past the
            # retry check — a retried task's returns never materialize)
            traced = self._traced_oids
            for oid, _k, _pl, _s in p["returns"]:
                traced[oid] = tr
            while len(traced) > 4096:  # FIFO bound (untraced push = ok)
                traced.pop(next(iter(traced)))
            t_done0 = time.monotonic()
        if spec is not None:
            # final completion: the quota admission charge comes back
            # (retries above keep it — the task is still in the system)
            self.fairsched.release_admission(spec.task_id)
        if spec is not None and not spec.is_actor_create:
            # actor-creation pins persist for the actor's lifetime
            # (restart replays the creation args); everything else
            # unpins on final completion
            self._unpin_deps(spec)
        if spec is not None and spec.actor_id is None and not spec.is_actor_create:
            for oid, kind, _, _ in p["returns"]:
                if kind == P.VAL_SHM:
                    if oid not in self._lineage:
                        self._lineage_order.append(oid)
                        while len(self._lineage_order) > 10000:
                            self._lineage.pop(self._lineage_order.popleft(), None)
                    self._lineage[oid] = spec
        prev_ev = self._task_event_index.get(p["task_id"], {})
        failed = (
            any(kind == P.VAL_ERROR for _, kind, _, _ in p["returns"])
            or prev_ev.get("state") == "FAILED"
        )
        ev = self._task_event(
            p["task_id"], state="FAILED" if failed else "FINISHED",
            finished_at=time.time(), t_finished=time.monotonic(),
        )
        if failed:
            # application error published to the caller (retries, if
            # any, were already consumed or not requested)
            self._bm_task_fail["value"] += 1
            self._record_event(
                "task_failed", task_id=p["task_id"].hex(),
                name=ev.get("name", ""),
                **({"trace_id": ev["trace_id"]} if "trace_id" in ev else {}),
            )
        owner_spec = spec if spec is not None else ispec
        owner_label = owner_spec.owner if owner_spec is not None else ""
        for oid, kind, payload, size in p["returns"]:
            self._object_ready(oid, kind, payload, size, node_id=node_id,
                               owner=owner_label)
        if tr is not None:
            # completion handling: return registration + readiness
            # fan-out (get/wait waiters, pushes) for this task
            self._emit_runtime_span(
                "hub.complete", "complete", tr, t_done0, time.monotonic(),
                task_id=p["task_id"].hex(),
            )
        self._dispatch()

    def _maybe_retry_app_error(self, spec, returns) -> bool:
        """retry_exceptions (reference: @ray.remote(retry_exceptions=...)):
        application errors normally publish immediately; with the option
        set (True, or a list of exception types) the task re-enqueues
        against its retry budget instead."""
        if (
            spec is None
            or spec.is_actor_create
            or spec.actor_id is not None
            or spec.retries_left <= 0
            or not spec.options.get("retry_exceptions")
            or not any(kind == P.VAL_ERROR for _, kind, _, _ in returns)
        ):
            return False
        allowed = spec.options["retry_exceptions"]
        if isinstance(allowed, bytes):
            # exception-class list ships as a cloudpickle blob
            # (remote_function.scheduling_options); unwrap once and
            # cache — retries re-enter this method
            try:
                allowed = loads_inline(allowed)
            except Exception:
                return False
            spec.options["retry_exceptions"] = allowed
        if isinstance(allowed, (list, tuple)):
            try:
                payload = next(
                    pl for _, kind, pl, _ in returns if kind == P.VAL_ERROR
                )
                err = loads_inline(payload)
                cause = getattr(err, "cause", None)
                match = isinstance(err, tuple(allowed)) or isinstance(
                    cause, tuple(allowed)
                )
            except Exception:
                match = False
            if not match:
                return False
        spec.retries_left -= 1
        self.tasks[spec.task_id] = spec
        self._task_event(spec.task_id, state="PENDING_RETRY")
        self._bm_task_retry["value"] += 1
        self._record_event(
            "task_retry", task_id=spec.task_id.hex(), reason="app_error",
            retries_left=spec.retries_left, **self._trace_fields(spec),
        )
        self._enqueue_runnable(spec)
        return True

    def _update_tenant_gauges(self) -> None:
        """Per-tenant share-of-running-work gauges (fairsched)."""
        tenants = self.fairsched.tenants
        total = sum(t.rate for t in tenants.values())
        for name, t in tenants.items():
            g = self._tenant_gauges.get(name)
            if g is None:
                g = self._tenant_gauges[name] = self._bm(
                    "ray_tpu_tenant_running_share", "gauge",
                    "tenant's share of currently running work "
                    "(chips, else CPUs)", (("tenant", name),))
            g["value"] = (t.rate / total) if total > 0 else 0.0
        for name in [n for n in self._tenant_gauges if n not in tenants]:
            # dropped tenant: delete the series (zeroing it would leak
            # one gauge per tenant name ever seen under client churn —
            # the registry-growth class GL009 polices)
            self._tenant_gauges.pop(name)
            self.metrics.pop(
                ("ray_tpu_tenant_running_share", (("tenant", name),)), None
            )

    def _release_task_resources(self, spec: TaskSpec):
        # the dispatch interval ends whenever the resources release
        # (done, failed, retried, preempted) — fold the fair-share
        # clock; the quota charge is released separately at FINAL
        # completion (release_admission). Settle is UNGATED: even with
        # every tenant pruned (driver churn), the task's _running entry
        # must pop or the engine leaks one per in-flight task (GL009).
        self.fairsched.settle(spec.task_id)
        # unconditionally: settle/release may have pruned the LAST
        # tenant, and the gauge sweep is what deletes its stale series
        self._update_tenant_gauges()
        pool = spec.options.pop("_pool", None)
        if pool is None:
            return
        kind, owner, bidx = pool
        if kind == "node":
            node = self.nodes.get(owner)
            if node is not None:
                self._release(spec.resources, node.avail)
        else:
            entry = self.pgs.get(owner)
            if entry is not None:
                self._release(spec.resources, entry.bundle_avail[bidx])

    def _fail_task(self, spec: TaskSpec, err: Exception):
        from .serialization import dumps_inline as d

        blob = d(err)
        for oid in spec.return_ids:
            self._object_ready(oid, P.VAL_ERROR, blob, 0)
        if spec.ready_id:
            self._object_ready(spec.ready_id, P.VAL_ERROR, blob, 0)
        if spec.options.get("streaming"):
            self._end_stream_with_error(spec.task_id, blob)
        self._task_event(spec.task_id, state="FAILED", finished_at=time.time(),
                         t_finished=time.monotonic(), error=str(err)[:200])
        self._bm_task_fail["value"] += 1
        self._record_event(
            "task_give_up", task_id=spec.task_id.hex(),
            name=spec.fn_id or (spec.method or ""), error=str(err)[:200],
            **self._trace_fields(spec),
        )
        self.tasks.pop(spec.task_id, None)
        self.fairsched.settle(spec.task_id)
        self.fairsched.release_admission(spec.task_id)
        self._unpin_deps(spec)
        if spec.is_actor_create and spec.actor_id is not None:
            # a failed CREATION must kill the actor entry too, or
            # queued method calls park in pending_calls forever with
            # the actor wedged in state "pending"
            actor = self.actors.get(spec.actor_id)
            if actor is not None and actor.state != "dead":
                actor.state = "dead"
                self._drain_actor_queue_with_error(actor)

    # ----- actors
    def _on_create_actor(self, conn, p):
        if p["actor_id"] in self.actors:
            # duplicate delivery: the entry exists — re-admitting the
            # creation spec would spawn a second worker for the same
            # actor id. (Named duplicates from DIFFERENT clients carry
            # different actor_ids and still hit the name check below.)
            return
        options = p["options"]
        entry = ActorEntry(
            actor_id=p["actor_id"],
            fn_id=p["fn_id"],
            args_kind=p["args_kind"],
            args_payload=p["args_payload"],
            resources=p["resources"],
            options=options,
            ready_id=p["ready_id"],
            name=options.get("name") or "",
            restarts_left=options.get("max_restarts", 0),
        )
        name = options.get("name")
        if name:
            key = (options.get("namespace") or "default", name)
            if key in self.named_actors and self.actors.get(self.named_actors[key], None) and self.actors[self.named_actors[key]].state != "dead":
                self._reply(conn, p["req_id"], error=f"Actor with name '{name}' already exists")
                return
            self.named_actors[key] = entry.actor_id
            self._reply(conn, p["req_id"], error=None)
        self.actors[entry.actor_id] = entry
        spec = TaskSpec(
            task_id=p["actor_id"],  # creation task id == actor id
            fn_id=p["fn_id"],
            args_kind=p["args_kind"],
            args_payload=p["args_payload"],
            return_ids=[],
            resources=p["resources"],
            options=dict(options),
            is_actor_create=True,
            actor_id=p["actor_id"],
            ready_id=p["ready_id"],
            owner=self._conn_label(conn),
        )
        self._admit(spec, p.get("arg_deps", []))

    def _on_actor_ready(self, conn, p):
        wid = self.conn_to_worker.get(conn)
        worker = self.workers.get(wid)
        actor = self.actors.get(p["actor_id"])
        spec = self.tasks.pop(p["actor_id"], None)
        if actor is None or worker is None:
            return
        if p.get("error") is not None:
            # constructor raised: actor is dead on arrival
            actor.state = "dead"
            self._task_event(
                p["actor_id"], state="FAILED",
                finished_at=time.time(), t_finished=time.monotonic(),
            )
            if spec is not None:
                self._release_task_resources(spec)
                self._unpin_deps(spec)
            worker.state = "idle"
            worker.actor_id = None
            worker.tpu_chips = ()  # chips remain pinned to the worker
            self._object_ready(actor.ready_id, P.VAL_ERROR, p["error"], 0)
            self._drain_actor_queue_with_error(actor)
            self._dispatch()
            return
        actor.state = "alive"
        actor.worker_id = wid
        self._task_event(
            p["actor_id"], state="FINISHED",
            finished_at=time.time(), t_finished=time.monotonic(),
        )
        # the creation spec is finalized but its arg pins must survive
        # for the actor's lifetime (restart replays the creation args):
        # transfer them to the actor entry. A restart's respawn spec
        # skips _admit, so pins are never doubled.
        if spec is not None and spec.pinned_deps:
            actor.creation_pins.extend(spec.pinned_deps)
            spec.pinned_deps = []
        worker.state = "actor"
        worker.actor_id = actor.actor_id
        worker.current_task = None
        # Actor creation resources stay held for the actor's lifetime.
        actor.pool = spec.options.get("_pool") if spec is not None else None
        self._object_ready(actor.ready_id, P.VAL_INLINE, dumps_inline((b"P\x80\x05N.", [])), 0)
        while actor.pending_calls:
            call = actor.pending_calls.popleft()
            self._forward_actor_call(actor, call)
        self._dispatch()

    def _on_submit_actor_task(self, conn, p):
        if p["task_id"] in self._task_event_index:
            return  # duplicate delivery: the call is already in flight
        actor = self.actors.get(p["actor_id"])
        spec = TaskSpec(
            task_id=p["task_id"],
            fn_id="",
            args_kind=p["args_kind"],
            args_payload=p["args_payload"],
            return_ids=p["return_ids"],
            resources={},
            options=p["options"],
            actor_id=p["actor_id"],
            method=p["method"],
            owner=self._conn_label(conn),
        )
        tr = p.get("trace")
        if tr is not None:
            spec.trace = (tr[0], tr[1])
        if actor is None or actor.state == "dead":
            from ..exceptions import ActorDiedError

            blob = dumps_inline(ActorDiedError(msg="Actor is dead."))
            for oid in spec.return_ids:
                self._object_ready(oid, P.VAL_ERROR, blob, 0)
            return
        deps = p.get("arg_deps", [])
        pending = 0
        for dep in deps:
            e = self.objects.get(dep)
            if e is None:
                e = self.objects[dep] = ObjEntry()
            e.pins += 1
            spec.pinned_deps.append(dep)
            if not e.ready:
                pending += 1
                self.dep_waiters.setdefault(dep, []).append(spec)
        spec.deps_remaining = pending
        spec.options["_actor_call"] = True
        ev = self._task_event(
            spec.task_id, name=spec.method or "",
            state="PENDING_ARGS" if pending else "PENDING_ACTOR",
            submitted_at=time.time(), t_submit=time.monotonic(),
        )
        if spec.trace is not None:
            ev["trace_id"] = spec.trace[0]
        if pending:
            self.tasks[spec.task_id] = spec
            return
        self._route_actor_call(actor, spec)

    def _route_actor_call(self, actor: ActorEntry, spec: TaskSpec):
        if actor.state == "alive":
            self._forward_actor_call(actor, spec)
        else:
            actor.pending_calls.append(spec)

    def _forward_actor_call(self, actor: ActorEntry, spec: TaskSpec):
        worker = self.workers.get(actor.worker_id)
        if worker is None or worker.conn is None:
            actor.pending_calls.append(spec)
            return
        actor.inflight[spec.task_id] = spec
        now_mono = time.monotonic()
        ev = self._task_event(
            spec.task_id, name=spec.method or "", state="RUNNING",
            started_at=time.time(), t_scheduled=now_mono,
            worker_id=worker.worker_id,
            node_id=worker.node_id, actor_id=actor.actor_id.hex(),
        )
        exec_payload = {
            "task_id": spec.task_id,
            "actor_id": actor.actor_id,
            "method": spec.method,
            "args_kind": spec.args_kind,
            "args_payload": spec.args_payload,
            "return_ids": spec.return_ids,
            "options": {
                k: v for k, v in spec.options.items()
                if k in ("streaming",
                         "_generator_backpressure_num_objects",
                         "tenant", "priority", "job_id")
            },
        }
        if spec.trace is not None:
            # actor calls have no runnable-queue phase; the queue_wait
            # span covers submit-arrival -> forward (dep waits and
            # pending_calls parking included)
            t0 = ev.get("t_submit")
            dispatch_span = self._emit_runtime_span(
                "hub.actor_route", "queue_wait", spec.trace,
                t0 if t0 is not None else now_mono, now_mono,
                task_id=spec.task_id.hex(), method=spec.method or "",
            )
            exec_payload["trace"] = (spec.trace[0], dispatch_span)
        self._send(worker.conn, P.EXEC_ACTOR_TASK, exec_payload)
        # execute deadline for actor calls too (method.options(timeout_s=)
        # or the cluster-wide watchdog): a hung actor worker never EOFs,
        # and without this every queued call on it wedges forever. The
        # kill takes the whole worker — under max_concurrency that is
        # the deadline's documented blast radius — and the normal death
        # path fails in-flight calls with ActorDiedError and restarts
        # the actor per its budget.
        timeout_s = spec.options.get("timeout_s") or (
            self.config.task_timeout_default_s
        )
        if timeout_s and timeout_s > 0:
            self._add_timer(
                float(timeout_s),
                lambda a=actor, w=worker, s=spec, t=float(timeout_s):
                    self._check_actor_exec_timeout(a, w, s, t),
            )

    def _check_actor_exec_timeout(self, actor: ActorEntry, worker: WorkerEntry,
                                  spec: TaskSpec, timeout_s: float) -> None:
        """The actor call is still in flight on the same incarnation
        past its deadline: SIGKILL the (possibly hung) worker; the
        worker-death path surfaces ActorDiedError to in-flight callers
        and restarts the actor per max_restarts."""
        if (
            actor.inflight.get(spec.task_id) is not spec
            or actor.worker_id != worker.worker_id
            or self.workers.get(worker.worker_id) is not worker
        ):
            return  # completed, or a different incarnation by now
        self._record_event(
            "task_timeout", task_id=spec.task_id.hex(),
            worker_id=worker.worker_id, actor_id=actor.actor_id.hex(),
            timeout_s=timeout_s, **self._trace_fields(spec),
        )
        self._force_kill_worker(worker)

    def _drain_actor_queue_with_error(self, actor: ActorEntry):
        from ..exceptions import ActorDiedError

        blob = dumps_inline(ActorDiedError(msg="The actor died before this call could run."))
        while actor.pending_calls:
            spec = actor.pending_calls.popleft()
            for oid in spec.return_ids:
                self._object_ready(oid, P.VAL_ERROR, blob, 0)
            if spec.options.get("streaming"):
                self._end_stream_with_error(spec.task_id, blob)
            self._unpin_deps(spec)
        for spec in actor.inflight.values():
            for oid in spec.return_ids:
                self._object_ready(oid, P.VAL_ERROR, blob, 0)
            if spec.options.get("streaming"):
                self._end_stream_with_error(spec.task_id, blob)
            self._unpin_deps(spec)
        actor.inflight.clear()
        # the actor is permanently dead here on every call path: drop
        # the creation-arg pins, release its quota admission, and push
        # a tombstone — beyond the cap the oldest dead actors leave the
        # registry (handler-grown tables must prune: graftlint GL009)
        self._unpin_ids(actor.creation_pins)
        actor.creation_pins = []
        self.fairsched.settle(actor.actor_id)
        self.fairsched.release_admission(actor.actor_id)
        self._dead_actors.append(actor.actor_id)
        while len(self._dead_actors) > 10000:
            old_id = self._dead_actors.popleft()
            old = self.actors.get(old_id)
            if old is None or old.state != "dead":
                continue  # reused id or resurrected entry: keep it
            self.actors.pop(old_id, None)
            key = (old.options.get("namespace") or "default", old.name)
            if old.name and self.named_actors.get(key) == old_id:
                self.named_actors.pop(key, None)

    def _on_kill_actor(self, conn, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return
        if p.get("no_restart", True):
            actor.restarts_left = 0
        worker = self.workers.get(actor.worker_id) if actor.worker_id else None
        if worker is not None:
            self._kill_worker(worker)
            self._worker_died(worker)
        elif p.get("no_restart", True):
            from ..exceptions import ActorDiedError

            # Constructor may already be running on a worker that hasn't
            # reported ACTOR_READY yet — kill that worker.
            for w in list(self.workers.values()):
                if w.current_task is not None and w.current_task.actor_id == actor.actor_id:
                    self._kill_worker(w)
                    self._worker_died(w)
                    return
            # Otherwise the creation is still queued: cancel it outright.
            spec = self.tasks.pop(actor.actor_id, None)
            if spec is not None:
                key = self._sched_class(spec)
                q = self.runnable.get(key)
                if q is not None and spec in q:
                    q.remove(spec)
                # the creation may be quota-parked instead of runnable
                if self.fairsched.unpark(spec):
                    self._refresh_pending_quota_gauge()
                self._unpin_deps(spec)
            actor.state = "dead"
            blob = dumps_inline(ActorDiedError(msg="The actor was killed before it started."))
            self._object_ready(actor.ready_id, P.VAL_ERROR, blob, 0)
            self._drain_actor_queue_with_error(actor)
            self._dispatch()

    def _kill_worker(self, w: WorkerEntry):
        if w.conn is not None:
            self._send(w.conn, P.KILL, {})
        if w.proc is not None:
            try:
                w.proc.terminate()
            except Exception:
                pass

    # ----- worker failure handling
    def _safe_disconnect(self, conn):
        """_handle_disconnect behind a last-resort guard: it runs from
        the reactor's except paths, where a raising cleanup would kill
        the hub thread (the very bug class it is cleaning up after)."""
        # drop the fd from the persistent selector FIRST — after
        # conn.close() the fileobj can't resolve its fileno, and a
        # stale registration would collide with a new accept that
        # reuses the fd number
        sel = self._selector
        if sel is not None:
            try:
                sel.unregister(conn)
            except (KeyError, ValueError, OSError):
                pass  # never registered, or already gone
        try:
            self._handle_disconnect(conn)
        except Exception:
            log_exc("hub disconnect cleanup error")
        finally:
            # the broad-except path reaches here with the socket still
            # live; without a close the peer never sees EOF and blocks
            # in recv forever (and the hub leaks the fd). Last line of
            # defense: nothing here may raise.
            try:
                conn.close()
            except Exception:
                pass

    def _handle_disconnect(self, conn):
        # (the selector registration — the poll interest set — is
        # dropped by _safe_disconnect before this runs)
        self._outbox.pop(conn, None)
        cid_ = id(conn)
        for key in [k for k in self._client_puts if k[0] == cid_]:
            f = self._client_puts.pop(key)
            if isinstance(f, tuple):
                # ('failed', msg) tombstone from _on_put_chunk — the
                # file is already closed and unlinked; touching .name
                # here used to raise AttributeError and kill the hub
                # thread on a mid-chunked-put disconnect
                continue
            try:
                name = f.name
                f.close()
                os.unlink(name)
            except OSError:
                pass
        for subs in self.subscribers.values():
            if conn in subs:
                subs.remove(conn)
        cid = id(conn)
        for key in [k for k in self._inflight_reqs if k[0] == cid]:
            del self._inflight_reqs[key]
        # readiness subscriptions die with the connection
        for oid in self._ready_watch_conns.pop(cid, ()):
            watchers = self._ready_watchers.get(oid)
            if watchers is not None:
                try:
                    watchers.remove(conn)
                except ValueError:
                    pass
                if not watchers:
                    del self._ready_watchers[oid]
        self.client_conns.pop(conn, None)
        self.fairsched.drop_conn(cid)
        # prune per-tenant gauges for tenants the drop removed (the
        # charge/settle sites are gated on live tenants and would
        # otherwise leave a stale last-value series forever)
        self._update_tenant_gauges()
        node_id = self.agent_conns.pop(conn, None)
        if node_id is not None:
            self._node_died(node_id)
            return
        wid = self.conn_to_worker.pop(conn, None)
        if wid is None:
            if conn is self.driver_conn:
                # driver died: shut the whole session down
                self._record_event("driver_disconnect")
                self._running = False
            else:
                # a remote client (Ray Client parity) going away is a
                # normal-but-notable event: its pending gets died with it
                self._record_event("client_disconnect")
            return
        worker = self.workers.pop(wid, None)
        if worker is None:
            return
        self._worker_died(worker)

    def _node_died(self, node_id: str):
        """Agent connection lost: the host is gone. Its workers' sockets
        EOF independently and go through _worker_died (task retry, actor
        restart — now free to land on surviving nodes). Reference:
        GcsNodeManager::OnNodeFailure."""
        node = self.nodes.get(node_id)
        if node is None:
            return
        node.alive = False
        node.agent_conn = None
        node.avail = {}
        node.spawning = 0
        node.spawning_actor = 0
        sys.stderr.write(f"[ray_tpu] node {node_id} died\n")
        self._record_event(
            "node_down", node_id=node_id, hostname=node.hostname,
            workers=sum(1 for w in self.workers.values()
                        if w.node_id == node_id),
        )
        # zero the dead node's gauges: a scrape must not keep showing
        # last-heartbeat RSS/load for a host that no longer exists
        self._node_stat_gauges(
            node_id, rss_bytes=0.0, cpu_load_1m=0.0, n_workers=0.0,
        )
        g = self._node_gauges.get(node_id)
        if g is not None:
            g[0]["value"] = 0.0  # store bytes
            g[1]["value"] = 0.0  # chips in use
        self._fail_fetches_for_node(node_id)
        # invalidate client-side location caches: any resolve pointing
        # at this node is stale and must re-resolve (replica or relay)
        if self.subscribers.get("__node_down__"):
            self._publish("__node_down__", {"node_id": node_id})
        self._dispatch()

    def _worker_died(self, worker: WorkerEntry):
        from ..exceptions import ActorDiedError, WorkerCrashedError

        worker.state = "dead"
        self._record_event(
            "worker_exit", worker_id=worker.worker_id,
            node_id=worker.node_id,
            actor_id=worker.actor_id.hex() if worker.actor_id else None,
            mid_task=worker.current_task is not None,
        )
        self.workers.pop(worker.worker_id, None)
        self.conn_to_worker.pop(worker.conn, None)
        wnode = self.nodes.get(worker.node_id)
        if worker.pinned_chips and wnode is not None:
            # chips reserved by a live SLICE PG stay out of the free
            # pool — they become placeable again through their bundle
            # (placement checks live-worker pins, not the free pool)
            wnode.free_tpu_chips.update(
                set(worker.pinned_chips) - wnode.pg_reserved_chips
            )
        spec = worker.current_task
        if spec is not None and spec.is_actor_create:
            # actor died mid-constructor: release the creation resources
            self._release_task_resources(spec)
        if spec is not None and not spec.is_actor_create:
            self._release_task_resources(spec)
            if spec.options.get("_cancelled"):
                from ..exceptions import TaskCancelledError

                self._fail_task(spec, TaskCancelledError("task was cancelled"))
            elif spec.options.get("_oom"):
                from ..exceptions import OutOfMemoryError

                self._fail_task(spec, OutOfMemoryError(
                    "worker exceeded the per-worker memory threshold "
                    f"({self.config.memory_usage_threshold:.0f} bytes)"))
            elif spec.options.pop("_preempted", False):
                # gang preemption: requeue with lineage intact WITHOUT
                # burning the crash-retry budget (the task did nothing
                # wrong; the scheduler took its chips back)
                self._bm_task_retry["value"] += 1
                self._record_event(
                    "task_retry", task_id=spec.task_id.hex(),
                    reason="preempted", retries_left=spec.retries_left,
                    **self._trace_fields(spec),
                )
                self._task_event(spec.task_id, state="PENDING_RETRY")
                self._enqueue_runnable(spec)
            elif spec.options.get("_timed_out"):
                # execute deadline (options(timeout_s=) / hung-worker
                # watchdog): the watchdog killed the worker. Retry
                # against the crash budget; past it, the error names
                # the timeout rather than a generic crash.
                timeout_s = spec.options.pop("_timed_out")
                if spec.retries_left > 0:
                    spec.retries_left -= 1
                    self._bm_task_retry["value"] += 1
                    self._record_event(
                        "task_retry", task_id=spec.task_id.hex(),
                        reason="timeout", retries_left=spec.retries_left,
                        **self._trace_fields(spec),
                    )
                    self._task_event(spec.task_id, state="PENDING_RETRY")
                    self._enqueue_runnable(spec)
                else:
                    from ..exceptions import TaskTimeoutError

                    self._fail_task(spec, TaskTimeoutError(
                        f"task exceeded its execute deadline of "
                        f"{timeout_s}s and its retry budget; the stalled "
                        f"worker was killed"
                    ))
            elif spec.retries_left > 0:
                spec.retries_left -= 1
                self._bm_task_retry["value"] += 1
                self._record_event(
                    "task_retry", task_id=spec.task_id.hex(),
                    reason="worker_died", retries_left=spec.retries_left,
                    **self._trace_fields(spec),
                )
                self._enqueue_runnable(spec)
            else:
                self._fail_task(spec, WorkerCrashedError("worker died while executing task"))
        if len(worker.assigned) > 1:
            # pipelined followers never started executing: requeue them
            # WITHOUT burning the crash-retry budget (only the head was
            # running). They hold no node resources until promotion, so
            # _release_task_resources only settles their fairshare clock.
            followers = list(worker.assigned)[1:]
            worker.assigned.clear()
            if spec is not None:
                worker.assigned.append(spec)  # head: handled above
            self._record_event(
                "pipeline_requeue", worker_id=worker.worker_id,
                count=len(followers),
            )
            for f in followers:
                self._release_task_resources(f)
                self._task_event(f.task_id, state="PENDING_RETRY")
                self._enqueue_runnable(f)
        if worker.actor_id or (spec is not None and spec.is_actor_create):
            actor_id = worker.actor_id or spec.actor_id
            actor = self.actors.get(actor_id)
            if actor is not None:
                if spec is not None and spec.is_actor_create and spec.pinned_deps:
                    # constructor died before _on_actor_ready transferred
                    # the creation-arg pins: move them to the actor entry
                    # so a restart keeps the args and permanent death
                    # (_drain_actor_queue_with_error) releases them
                    actor.creation_pins.extend(spec.pinned_deps)
                    spec.pinned_deps = []
                # release actor lifetime resources to the pool they came from
                if actor.state == "alive":
                    if actor.pool is not None and actor.pool[0] == "pg":
                        entry = self.pgs.get(actor.pool[1])
                        if entry is not None:
                            self._release(actor.resources, entry.bundle_avail[actor.pool[2]])
                    else:
                        home = self.nodes.get(
                            actor.pool[1] if actor.pool else worker.node_id
                        )
                        if home is not None:
                            self._release(actor.resources, home.avail)
                    actor.pool = None
                    self.fairsched.settle(actor.actor_id)
                if actor.restarts_left != 0 or worker.preempted:
                    # preemption restarts through this same path but
                    # never burns the restart budget (existing
                    # actor_restart machinery, reference semantics)
                    if actor.restarts_left > 0 and not worker.preempted:
                        actor.restarts_left -= 1
                    actor.state = "restarting"
                    actor.worker_id = None
                    self._record_event(
                        "actor_restart", actor_id=actor.actor_id.hex(),
                        name=actor.name, restarts_left=actor.restarts_left,
                    )
                    # in-flight calls fail; queued calls run on the new incarnation
                    blob = dumps_inline(ActorDiedError(msg="Actor died; call was in flight."))
                    for s in actor.inflight.values():
                        for oid in s.return_ids:
                            self._object_ready(oid, P.VAL_ERROR, blob, 0)
                        if s.options.get("streaming"):
                            self._end_stream_with_error(s.task_id, blob)
                        self._unpin_deps(s)
                    actor.inflight.clear()
                    respawn_opts = dict(actor.options)
                    # the new incarnation can tell it is a restart
                    # (get_runtime_context().was_current_actor_reconstructed)
                    respawn_opts["_restarted"] = True
                    respawn = TaskSpec(
                        task_id=actor.actor_id,
                        fn_id=actor.fn_id,
                        args_kind=actor.args_kind,
                        args_payload=actor.args_payload,
                        return_ids=[],
                        resources=actor.resources,
                        options=respawn_opts,
                        is_actor_create=True,
                        actor_id=actor.actor_id,
                        ready_id=actor.ready_id,
                    )
                    self.tasks[respawn.task_id] = respawn
                    self._enqueue_runnable(respawn)
                else:
                    actor.state = "dead"
                    self._drain_actor_queue_with_error(actor)
            else:
                # actor entry already gone: nothing can restart, drop
                # any creation-arg pins still on the spec
                self._unpin_deps(spec)
        self._dispatch()

    def _on_cancel(self, conn, p):
        """Cancel a task by one of its return objects. Queued tasks are
        dequeued and failed; RUNNING tasks are interrupted — SIGINT for
        the cooperative path, worker kill for force=True (reference:
        ray.cancel force semantics, core_worker CancelTask)."""
        oid = p["object_id"]
        force = p.get("force", False)
        from ..exceptions import TaskCancelledError

        for q in self.runnable.values():
            for spec in q:
                if oid in spec.return_ids:
                    q.remove(spec)
                    self.tasks.pop(spec.task_id, None)
                    self._fail_task(spec, TaskCancelledError("task was cancelled"))
                    return
        # quota-parked tasks (fairsched pending_quota)
        for spec in self.fairsched.parked_specs():
            if oid in spec.return_ids:
                self.fairsched.unpark(spec)
                self._refresh_pending_quota_gauge()
                self.tasks.pop(spec.task_id, None)
                self._fail_task(spec, TaskCancelledError("task was cancelled"))
                return
        # queued actor calls
        for actor in self.actors.values():
            for spec in list(actor.pending_calls):
                if oid in spec.return_ids:
                    actor.pending_calls.remove(spec)
                    self._fail_task(spec, TaskCancelledError("task was cancelled"))
                    return
        # actor calls already forwarded to the worker: mark them
        # cancelled worker-side (the worker drops them at dequeue; the
        # one currently executing cannot be cooperatively stopped)
        for actor in self.actors.values():
            for spec in actor.inflight.values():
                if oid in spec.return_ids:
                    worker = self.workers.get(actor.worker_id)
                    if worker is not None and worker.conn is not None:
                        self._send(worker.conn, P.CANCEL_TASK,
                                   {"task_id": spec.task_id,
                                    "return_ids": spec.return_ids})
                    return
        # pipelined followers queued in a worker's own task queue: drop
        # at dequeue (CANCEL_TASK marks it worker-side) and fail here —
        # they never started, hold no node resources, and need no
        # interrupt
        for w in self.workers.values():
            for spec in list(w.assigned)[1:]:
                if oid in spec.return_ids:
                    w.assigned.remove(spec)
                    if w.conn is not None:
                        self._send(w.conn, P.CANCEL_TASK,
                                   {"task_id": spec.task_id,
                                    "return_ids": spec.return_ids})
                    self.tasks.pop(spec.task_id, None)
                    self._fail_task(spec, TaskCancelledError("task was cancelled"))
                    return
        # running task: interrupt its worker
        for w in self.workers.values():
            spec = w.current_task
            if spec is not None and oid in spec.return_ids:
                spec.options["_cancelled"] = True
                spec.retries_left = 0
                if force:
                    self._kill_worker(w)
                elif w.proc is not None:
                    import signal

                    try:
                        w.proc.send_signal(signal.SIGINT)
                    except Exception:
                        pass
                # running on a remote node without force: best-effort
                # no-op (the reference likewise cannot interrupt
                # arbitrary native code without force)
                return

    # ----- placement groups
    def _on_create_pg(self, conn, p):
        from .ids import PlacementGroupID

        bundles = p["bundles"]
        strategy = p["strategy"]
        if strategy == "SLICE":
            # SLICE must fail loudly where it cannot deliver its promise
            # (ICI-contiguous chips), never degrade to SPREAD silently
            for b in bundles:
                t = b.get("TPU", 0)
                if t != int(t) or int(t) < 1:
                    self._reply(
                        conn, p["req_id"],
                        error="SLICE bundles must request whole TPU "
                              f"chips (>=1); got {b}",
                        pg_id=None,
                    )
                    return
            if not any(
                n.alive and n.chip_coords for n in self.nodes.values()
            ):
                self._reply(
                    conn, p["req_id"],
                    error="SLICE requires ICI topology, but no alive "
                          "node reports chip coordinates (set "
                          "TPU_TOPOLOGY or TPU_CHIP_COORDS)",
                    pg_id=None,
                )
                return
        if strategy == "STRICT_SPREAD" and len(bundles) > len(
            [n for n in self.nodes.values() if n.alive]
        ):
            self._reply(
                conn, p["req_id"],
                error=f"STRICT_SPREAD needs {len(bundles)} nodes, have "
                      f"{sum(1 for n in self.nodes.values() if n.alive)}",
                pg_id=None,
            )
            return
        pg_id = PlacementGroupID.generate().binary()
        # PG reservations hold resources exclusively — they count
        # against the tenant's quota like admitted tasks (and tasks
        # placed INTO the PG are exempt, so nothing double-counts).
        # Over-quota reservations fail fast instead of queueing.
        quota_err = self.fairsched.charge_reservation(
            pg_id, p.get("tenant") or "default",
            _sum_bundle_resources(bundles),
        )
        if quota_err is not None:
            self._reply(conn, p["req_id"], error=quota_err, pg_id=None)
            return
        entry = PGEntry(
            pg_id=pg_id,
            bundles=bundles,
            strategy=strategy,
            name=p.get("name", ""),
            ready=False,
            bundle_avail=[dict(b) for b in bundles],
            tenant=p.get("tenant") or "default",
            priority=self.fairsched.priority_of(p),
            job_id=p.get("job_id") or "",
            seq=next(self._pg_counter),
        )
        self.pgs[pg_id] = entry
        self._try_reserve_pg(entry)
        self._reply(conn, p["req_id"], pg_id=pg_id)

    def _try_reserve_pg(self, entry: PGEntry):
        """Reserve a PG's bundles, preempting lower-priority gangs when
        the reservation cannot fit (fairsched). A freshly-preempted PG
        stands aside (yield_to) until its beneficiary's reservation
        lands, so victims can't re-grab the chips they were taken off."""
        if entry.ready:
            return
        if entry.yield_to is not None:
            ben = self.pgs.get(entry.yield_to)
            if (
                ben is not None
                and not ben.ready
                and time.monotonic() < entry.yield_until
            ):
                return
            # beneficiary seated, vanished, or overstayed its window
            # (it may never become schedulable): stop standing aside
            entry.yield_to = None
        self._reserve_pg_attempt(entry)
        if entry.ready:
            return
        # Preemption sweep under the dispatch guard: _worker_died runs
        # _dispatch at the end of every victim kill, and on the
        # _on_create_pg/_on_pg_ready entry paths (outside a _dispatch
        # frame) that dispatch would re-place freed chips — or requeue
        # gang tasks into the still-ready victim PG — before the
        # beneficiary's re-reservation gets its turn, defeating the
        # preemption. Holding the flag defers those dispatches to one
        # pass AFTER the reservation retry.
        was_dispatching = self._dispatching
        self._dispatching = True
        try:
            preempted = self._preempt_for_pg(entry)
            if preempted:
                # victims died synchronously on this thread: their
                # chips and resources are back — retry right now
                entry.preempt_rounds += 1
                self._reserve_pg_attempt(entry)
        finally:
            self._dispatching = was_dispatching
        if entry.ready:
            entry.preempt_rounds = 0
        if preempted and not was_dispatching:
            self._dispatch()  # run the kills' deferred dispatch work

    def _reserve_pg_attempt(self, entry: PGEntry):
        """Assign each bundle to a node and acquire its resources — the
        reference's 2-phase GcsPlacementGroupScheduler collapsed to one
        atomic pass over the hub's authoritative node table
        (gcs_placement_group_scheduler.h:122; bundle packing policies
        src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h)."""
        if entry.ready:
            return
        nodes = self._ordered_nodes()
        if not nodes:
            return
        if entry.strategy == "SLICE":
            self._try_reserve_slice(entry, nodes)
            return
        snap = {n.node_id: dict(n.avail) for n in nodes}
        assign: List[str] = []
        if entry.strategy in ("PACK", "STRICT_PACK"):
            total = _sum_bundle_resources(entry.bundles)
            for n in nodes:
                if self._resources_fit(total, snap[n.node_id]):
                    assign = [n.node_id] * len(entry.bundles)
                    break
            if not assign and entry.strategy == "STRICT_PACK":
                return  # stays pending until one node can host everything
        if not assign:
            # SPREAD / STRICT_SPREAD / PACK-fallback: greedy round-robin,
            # STRICT_SPREAD additionally requires distinct nodes
            distinct = entry.strategy == "STRICT_SPREAD"
            used: Set[str] = set()
            start = 0
            for b in entry.bundles:
                placed_on = None
                for off in range(len(nodes)):
                    n = nodes[(start + off) % len(nodes)]
                    if distinct and n.node_id in used:
                        continue
                    if self._resources_fit(b, snap[n.node_id]):
                        placed_on = n.node_id
                        break
                if placed_on is None:
                    return  # infeasible now; stays pending
                self._acquire(b, snap[placed_on])
                used.add(placed_on)
                assign.append(placed_on)
                start += 1
        # commit: move resources from the nodes into the bundles
        for b, nid in zip(entry.bundles, assign):
            self._acquire(b, self.nodes[nid].avail)
        entry.bundle_nodes = assign
        entry.ready = True

    def _try_reserve_slice(self, entry: PGEntry, nodes: List[NodeEntry]):
        """SLICE: reserve ICI-contiguous chips. One host => one simple
        path through the free-chip mesh split into per-bundle chunks;
        bigger gangs => one bundle per host, each host-contiguous (the
        cross-host hop rides DCN either way, so only intra-host
        contiguity matters). The reference has no equivalent — its TPU
        story stops at pod-name gang resources
        (python/ray/_private/accelerators/tpu.py:352-375)."""
        need = [int(b.get("TPU", 0)) for b in entry.bundles]
        total = sum(need)
        topo_nodes = [n for n in nodes if n.chip_coords]
        # 1) whole gang on one host, one contiguous path
        total_res = _sum_bundle_resources(entry.bundles)
        for n in topo_nodes:
            if not self._resources_fit(total_res, n.avail):
                continue
            path = _find_chip_path(n.chip_coords, n.free_tpu_chips, total)
            if path is None:
                continue
            i = 0
            chunks = []
            for k in need:
                chunks.append(tuple(path[i:i + k]))
                i += k
            self._commit_slice(entry, [n.node_id] * len(need), chunks)
            return
        # 2) one bundle per host, distinct hosts, each chunk contiguous
        # (preferred over mixed packing: bundle ranks map 1:1 onto
        # hosts, the layout multihost jobs expect)
        if len(topo_nodes) >= len(entry.bundles):
            plan: List[Tuple[NodeEntry, tuple]] = []
            used: Set[str] = set()
            feasible = True
            for b, k in zip(entry.bundles, need):
                found = None
                for n in topo_nodes:
                    if n.node_id in used:
                        continue
                    if not self._resources_fit(b, n.avail):
                        continue
                    path = _find_chip_path(
                        n.chip_coords, n.free_tpu_chips, k
                    )
                    if path is not None:
                        found = (n, tuple(path))
                        break
                if found is None:
                    feasible = False
                    break
                used.add(found[0].node_id)
                plan.append(found)
            if feasible:
                self._commit_slice(
                    entry,
                    [n.node_id for n, _ in plan],
                    [chunk for _, chunk in plan],
                )
                return
        # 3) mixed packing: k bundles per host, each bundle's chunk
        # host-contiguous. Greedy largest-first over per-host planned
        # copies of free chips/resources — places gangs that fragment
        # past cases 1 and 2 (e.g. 3x2-chip bundles on one fragmented
        # 8-chip host, or 4 bundles over 2 hosts).
        order = sorted(range(len(need)), key=lambda i: -need[i])
        planned_free = {n.node_id: set(n.free_tpu_chips) for n in topo_nodes}
        planned_avail = {n.node_id: dict(n.avail) for n in topo_nodes}
        mixed: List[Optional[Tuple[str, tuple]]] = [None] * len(need)
        for idx in order:
            b, k = entry.bundles[idx], need[idx]
            for n in topo_nodes:
                if not self._resources_fit(b, planned_avail[n.node_id]):
                    continue
                if k == 0:
                    mixed[idx] = (n.node_id, ())
                    self._acquire(b, planned_avail[n.node_id])
                    break
                path = _find_chip_path(
                    n.chip_coords, planned_free[n.node_id], k
                )
                if path is None:
                    continue
                mixed[idx] = (n.node_id, tuple(path))
                self._acquire(b, planned_avail[n.node_id])
                planned_free[n.node_id].difference_update(path)
                break
            if mixed[idx] is None:
                return  # infeasible now; stays pending
        self._commit_slice(
            entry,
            [a[0] for a in mixed],
            [a[1] for a in mixed],
        )

    def _commit_slice(self, entry: PGEntry, assign: List[str],
                      chunks: List[tuple]):
        for b, nid, chunk in zip(entry.bundles, assign, chunks):
            node = self.nodes[nid]
            self._acquire(b, node.avail)
            node.free_tpu_chips.difference_update(chunk)
            node.pg_reserved_chips.update(chunk)
        entry.bundle_nodes = assign
        entry.bundle_chips = chunks
        entry.ready = True

    def _on_remove_pg(self, conn, p):
        entry = self.pgs.pop(p["pg_id"], None)
        if entry is not None:
            self._release_pg_reservation(entry)
            self.fairsched.release_admission(entry.pg_id)
        self._dispatch()

    def _release_pg_reservation(self, entry: PGEntry):
        """Return a ready PG's bundles (and SLICE chips) to their nodes
        and reset the entry to the unreserved state. Used by PG removal
        and by gang preemption (where the entry stays registered so the
        victim can re-reserve later)."""
        if not entry.ready:
            return
        for b, nid in zip(entry.bundles, entry.bundle_nodes):
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                self._release(b, node.avail)
        if entry.bundle_chips:
            for nid, chunk in zip(entry.bundle_nodes, entry.bundle_chips):
                node = self.nodes.get(nid)
                if node is None:
                    continue
                node.pg_reserved_chips.difference_update(chunk)
                # chips pinned by IDLE pooled workers come back
                # immediately (kill the worker — its jax binding is
                # useless outside the removed PG); busy/actor
                # workers release theirs on death (see _worker_died)
                pinned = set()
                for w in list(self.workers.values()):
                    if w.node_id != nid or not w.pinned_chips:
                        continue
                    if (
                        w.state == "idle"
                        and w.actor_id is None
                        and set(w.pinned_chips) & set(chunk)
                    ):
                        self._kill_worker(w)
                        self._worker_died(w)
                        continue
                    pinned.update(w.pinned_chips)
                node.free_tpu_chips.update(set(chunk) - pinned)
        entry.ready = False
        entry.bundle_avail = [dict(b) for b in entry.bundles]
        entry.bundle_nodes = []
        entry.bundle_chips = []

    # ----- gang preemption (fairsched)
    # one window bounds both sides of a preemption: a beneficiary may
    # not preempt again, and its victims stand aside (yield_to), for
    # this long — so a mis-estimated reservation can neither kill-storm
    # nor starve its victims past the window
    _PREEMPT_BACKOFF_S = 10.0
    # and after this many victim rounds without seating, the
    # beneficiary stops preempting entirely (preemption_gave_up event)
    _PREEMPT_MAX_ROUNDS = 2

    def _preempt_for_pg(self, entry: PGEntry) -> bool:
        """A reservation cannot fit: reclaim capacity from strictly
        lower-priority work — whole gangs (ready PGs) or single running
        plain tasks, lowest priority first, never partial gangs. The
        kills ride the existing retry/restart machinery, so preempted
        tasks requeue with lineage intact and preempted actors restart
        (actor_restart path). Returns True if anything was preempted."""
        pri = int(entry.priority or 0)
        now = time.monotonic()
        if now - entry.last_preempt_t < self._PREEMPT_BACKOFF_S:
            # this reservation already attempted preemption recently —
            # the 50ms pg_ready poll must not turn a stuck reservation
            # into a rolling kill storm (or a repeated O(workers+pgs)
            # candidate sweep)
            return False
        if entry.preempt_rounds >= self._PREEMPT_MAX_ROUNDS:
            # shed victims twice and still not seated: the feasibility
            # estimate is wrong for this cluster shape — stop
            # destroying lower-priority work (recorded once below)
            if entry.preempt_rounds == self._PREEMPT_MAX_ROUNDS:
                entry.preempt_rounds += 1
                self._record_event(
                    "preemption_gave_up", pg_id=entry.pg_id.hex(),
                    tenant=entry.tenant, priority=entry.priority,
                    rounds=self._PREEMPT_MAX_ROUNDS,
                )
            return False
        # arm the backoff for EVERY attempt — including one that finds
        # no candidates — so a reservation waiting on its 50ms poll
        # pays this sweep at most once per window
        entry.last_preempt_t = now
        pg_cands = [
            g for g in self.pgs.values()
            if g.ready and g is not entry and int(g.priority or 0) < pri
        ]
        task_cands: List[Tuple[WorkerEntry, TaskSpec]] = []
        for w in self.workers.values():
            spec = w.current_task
            if (
                spec is None
                or spec.is_actor_create
                or spec.options.get("placement_group")
            ):
                continue  # PG-resident work dies with its gang, not alone
            if self.fairsched.priority_of(spec.options) < pri:
                task_cands.append((w, spec))
        if not pg_cands and not task_cands:
            return False
        need_chips = sum(int(b.get("TPU", 0)) for b in entry.bundles)
        max_bundle = max(
            entry.bundles, key=lambda b: int(b.get("TPU", 0)),
            default={},
        )
        need_res = _sum_bundle_resources(entry.bundles)
        free_by_node: Dict[str, int] = {}
        avail_by_node: Dict[str, Dict[str, float]] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            free_by_node[n.node_id] = len(n.free_tpu_chips)
            avail_by_node[n.node_id] = dict(n.avail)
        victim_pgs, victim_tasks = self.fairsched.preemption_victims(
            pri, need_chips, max_bundle, need_res, pg_cands,
            task_cands, free_by_node, avail_by_node,
        )
        for w, spec in victim_tasks:
            self._bm_preemptions["value"] += 1
            self.fairsched.note_preemption(spec.options)
            self._record_event(
                "preemption", gang="task", task_id=spec.task_id.hex(),
                tenant=spec.options.get("tenant") or "default",
                priority=self.fairsched.priority_of(spec.options),
                by_pg=entry.pg_id.hex(), by_priority=pri,
                by_tenant=entry.tenant, **self._trace_fields(spec),
            )
            spec.options["_preempted"] = True
            w.preempted = True
            self._kill_worker(w)
            self._worker_died(w)
        for pg in victim_pgs:
            self._preempt_pg(pg, entry)
        return bool(victim_pgs or victim_tasks)

    def _preempt_pg(self, victim: PGEntry, beneficiary: PGEntry):
        """Preempt one whole gang: kill every worker running a task or
        hosting an actor placed in the victim PG (their specs requeue /
        actors restart without burning budgets), then release the
        reservation. The victim stands aside (yield_to) until the
        beneficiary's reservation is ready, then re-reserves and its
        requeued gang resumes."""
        self._bm_preemptions["value"] += 1
        self.fairsched.note_preemption(
            {"tenant": victim.tenant, "job_id": victim.job_id}
        )
        self._record_event(
            "preemption", gang="pg", pg_id=victim.pg_id.hex(),
            name=victim.name, tenant=victim.tenant,
            priority=victim.priority, by_pg=beneficiary.pg_id.hex(),
            by_priority=beneficiary.priority, by_tenant=beneficiary.tenant,
        )
        victim.yield_to = beneficiary.pg_id
        victim.yield_until = time.monotonic() + self._PREEMPT_BACKOFF_S
        for w in list(self.workers.values()):
            spec = w.current_task
            in_gang = False
            if spec is not None:
                pgopt = spec.options.get("placement_group")
                in_gang = bool(pgopt) and pgopt[0] == victim.pg_id
            if not in_gang and w.actor_id:
                actor = self.actors.get(w.actor_id)
                in_gang = (
                    actor is not None
                    and actor.pool is not None
                    and actor.pool[0] == "pg"
                    and actor.pool[1] == victim.pg_id
                )
            if not in_gang:
                continue
            if spec is not None and not spec.is_actor_create:
                spec.options["_preempted"] = True
            w.preempted = True
            self._kill_worker(w)
            self._worker_died(w)
        self._release_pg_reservation(victim)

    def _on_pg_ready(self, conn, p):
        entry = self.pgs.get(p["pg_id"])
        if entry is None:
            self._reply(conn, p["req_id"], ready=False)
            return
        self._try_reserve_pg(entry)
        if entry.ready:
            self._reply(conn, p["req_id"], ready=True)
            return
        deadline = time.monotonic() + (p.get("timeout") or 3600.0)
        req_id = p["req_id"]

        def poll(entry=entry, conn=conn, req_id=req_id, deadline=deadline):
            self._try_reserve_pg(entry)
            if entry.ready:
                self._reply(conn, req_id, ready=True)
            elif time.monotonic() > deadline:
                self._reply(conn, req_id, ready=False)
            else:
                self._add_timer(0.05, poll)

        self._add_timer(0.05, poll)

    # ----- introspection
    def _on_get_actor(self, conn, p):
        key = (p.get("namespace") or "default", p["name"])
        aid = self.named_actors.get(key)
        if aid is not None and self.actors.get(aid) and self.actors[aid].state == "dead":
            aid = None
        self._reply(conn, p["req_id"], actor_id=aid)

    def _on_cluster_resources(self, conn, p):
        res: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            src_pool = n.avail if p.get("available") else n.total
            for k, v in src_pool.items():
                res[k] = res.get(k, 0.0) + v
        self._reply(conn, p["req_id"], resources=res)

    def _on_list_state(self, conn, p):
        kind = p["kind"]
        items: List[dict] = []
        if kind == "actors":
            for a in self.actors.values():
                items.append(
                    {
                        "actor_id": a.actor_id.hex(),
                        "state": a.state.upper(),
                        "name": a.name,
                        "resources": a.resources,
                    }
                )
        elif kind == "workers":
            for w in self.workers.values():
                items.append({
                    "worker_id": w.worker_id, "state": w.state,
                    "node_id": w.node_id,
                    "pid": w.proc.pid if w.proc else w.pid,
                })
        elif kind == "tasks":
            items = list(self.task_events)
        elif kind == "events":
            items = list(self.events)
        elif kind == "traces":
            tid = p.get("trace_id")
            if tid:
                # one trace's raw spans (the CLI/dashboard run the
                # critical-path analyzer client-side on these)
                items = [dict(s) for s in self._trace_index.get(tid, ())]
            else:
                # running summaries (maintained in _record_span): the
                # overview never rescans every stored span dict
                for summ in self._trace_summaries.values():
                    items.append({
                        "trace_id": summ["trace_id"],
                        "n_spans": summ["n_spans"],
                        "start": summ["start"],
                        # anchored-monotonic stamps (util/tracing
                        # wall_at), so the difference IS a duration
                        "duration_s": summ["end"] - summ["start"],
                        "root": summ["root"],
                        "processes": len(summ["procs"]),
                    })
        elif kind == "metrics":
            self._merge_shard_metrics()
            for m in self.metrics.values():
                items.append(dict(m, buckets=[list(b) for b in m["buckets"]]))
        elif kind == "shards":
            # control-plane topology: one row per reactor shard plus a
            # row per state service (sharded mode; a single-reactor hub
            # reports its one implicit shard)
            if self._shards:
                for s in self._shards:
                    # same scrape-time monotonic-counter read as
                    # _merge_shard_metrics (see the note there)
                    st = s.stats  # graftlint: disable=GL013 — scrape-time monotonic counter read
                    items.append({
                        "shard": s.idx, "conns": st.conns,
                        "accepted": st.accepted, "wakeups": st.wakeups,
                        "frames_sent": st.frames_sent,
                        "drain_saturated": st.drain_saturated,
                        "backpressure": st.backpressure,
                    })
                for name, svc in self.state_services.items():
                    items.append({
                        "service": name, "processed": svc.processed,
                    })
            else:
                # same semantics as a shard's st.conns: every registered
                # socket (workers, agents, drivers, clients) — derived
                # from the live selector map minus the listener entry
                sel = self._selector
                n_conns = (
                    max(0, len(sel.get_map()) - 1) if sel is not None else 0
                )
                items.append({
                    "shard": 0,
                    "conns": n_conns,
                    "wakeups": int(self._bm_wakeups["value"]),
                    "frames_sent": int(self._bm_flushes["value"]),
                })
        elif kind == "timeline":
            # chrome://tracing "complete" events (reference: ray.timeline
            # via GCS task events -> chrome trace). Wall stamps position
            # the slices; durations come from the monotonic t_* twins
            # (GL008: a wall-clock delta is not a duration).
            now_mono = time.monotonic()
            for ev in self.task_events:
                if "started_at" not in ev:
                    continue
                t_sched = ev.get("t_scheduled")
                t_fin = ev.get("t_finished")
                dur_s = 0.0
                if t_sched is not None:
                    dur_s = (t_fin if t_fin is not None else now_mono) - t_sched
                items.append({
                    "name": ev.get("name", ""),
                    "cat": "task",
                    "ph": "X",
                    "ts": ev["started_at"] * 1e6,
                    "dur": max(0.0, dur_s * 1e6),
                    "pid": ev.get("node_id", "node0"),
                    "tid": ev.get("worker_id", ""),
                    "args": {"task_id": ev["task_id"],
                             "state": ev.get("state")},
                })
                # state-transition slice: the queued phase rendered
                # alongside the run slice so a saturated scheduler is
                # visible at a glance. Same fallback chain as the
                # placement metric and summarize_tasks: retries
                # re-stamp t_queued, and the first attempt's RUN time
                # must not render as the retry's queue wait. The slice
                # is end-aligned to the dispatch moment (started_at).
                t0 = ev.get("t_queued") or ev.get("t_submit")
                if (t0 is not None and t_sched is not None
                        and "submitted_at" in ev):
                    qdur = max(0.0, (t_sched - t0) * 1e6)
                    items.append({
                        "name": f"{ev.get('name', '')} [queued]",
                        "cat": "task_state",
                        "ph": "X",
                        "ts": ev["started_at"] * 1e6 - qdur,
                        "dur": qdur,
                        "pid": ev.get("node_id", "node0"),
                        "tid": ev.get("worker_id", ""),
                        "args": {"task_id": ev["task_id"],
                                 "transition": "SUBMITTED->RUNNING"},
                    })
            for sp in self.spans:
                items.append({
                    "name": sp.get("name", ""),
                    "cat": "span",
                    "ph": "X",
                    "ts": sp["start"] * 1e6,
                    "dur": max(0.0, (sp["end"] - sp["start"]) * 1e6),
                    "pid": sp.get("node_id", "node0"),
                    "tid": f"pid={sp.get('pid', '')}",
                    "args": {
                        "trace_id": sp.get("trace_id"),
                        "span_id": sp.get("span_id"),
                        "parent_id": sp.get("parent_id"),
                        **(sp.get("attrs") or {}),
                    },
                })
        elif kind == "placement_groups":
            for g in self.pgs.values():
                items.append({
                    "pg_id": g.pg_id.hex(),
                    "strategy": g.strategy,
                    "ready": g.ready,
                    "bundles": g.bundles,
                    "bundle_nodes": list(g.bundle_nodes),
                    "bundle_chips": [list(c) for c in g.bundle_chips],
                })
        elif kind == "objects":
            now_mono = time.monotonic()
            for oid, e in self.objects.items():
                items.append({
                    "object_id": oid.hex(), "ready": e.ready,
                    "size": e.size, "kind": e.kind,
                    "node_id": e.node_id,
                    "owner": e.owner,
                    "owner_alive": self._owner_alive(e.owner),
                    "age_s": max(0.0, now_mono - e.created_t),
                    "pins": e.pins,
                    "spilled": e.spilled,
                })
        elif kind == "profile":
            # folded profiler samples + per-process sampler meta rows.
            # Task names join through the task-event index (both sides
            # key on hex task ids).
            names: Dict[str, str] = {}
            for ev in self.task_events:
                nm = ev.get("name")
                if nm:
                    names[ev["task_id"]] = nm
            for skey, n in self.profile_samples.items():
                pid, pkind, domain, stage, task, stack = skey
                items.append({
                    "pid": pid, "kind": pkind, "thread": domain,
                    "stage": stage, "task_id": task,
                    "task_name": names.get(task, ""),
                    "stack": stack, "samples": n,
                })
            now_mono = time.monotonic()
            for pid, meta in self.profile_procs.items():
                items.append({
                    "proc": True, "pid": pid, "kind": meta["kind"],
                    "overhead": meta["overhead"], "hz": meta["hz"],
                    "idle_s": max(0.0, now_mono - meta["last_t"]),
                    "drops": self._profile_drops,
                })
        elif kind == "demand":
            # pending resource demand by shape (reference: the load the
            # raylet reports to the GCS for the autoscaler,
            # autoscaler/v2 ClusterStatus.resource_demands)
            shapes: Dict[tuple, int] = {}
            for q in self.runnable.values():
                for spec in q:
                    key = tuple(sorted(spec.resources.items()))
                    shapes[key] = shapes.get(key, 0) + 1
            for key, count in shapes.items():
                items.append({"shape": dict(key), "count": count})
            # quota-parked work is visible but flagged: the autoscaler
            # must NOT buy nodes for demand an admission quota blocks
            # (post-quota demand, not raw queue depth)
            pshapes: Dict[tuple, int] = {}
            for spec in self.fairsched.parked_specs():
                key = tuple(sorted(spec.resources.items()))
                pshapes[key] = pshapes.get(key, 0) + 1
            for key, count in pshapes.items():
                items.append({
                    "shape": dict(key), "count": count,
                    "pending_quota": True,
                })
        elif kind == "chaos":
            # fault-injection plane: the active plan + trigger counts
            # first, then recent fault events from the flight recorder
            # (chaos_* kinds plus the recovery events they provoke)
            if self._chaos is not None:
                snap = self._chaos.snapshot()
                items.append({
                    "plan": snap["plan"], "seed": snap["seed"],
                    "armed": snap["armed"],
                    "elapsed_s": snap["elapsed_s"],
                    "counts": snap["counts"],
                    "pending_timed": snap["pending_timed"],
                    "partitions": snap["partitions"],
                })
            fault_kinds = ("task_timeout", "node_heartbeat_miss")
            for ev in self.events:
                k = ev.get("kind", "")
                if k.startswith("chaos_") or k in fault_kinds:
                    items.append(dict(ev))
        elif kind == "jobs":
            items = self.fairsched.job_table()
        elif kind == "tenants":
            items = self.fairsched.tenant_table()
        elif kind == "nodes":
            for n in self.nodes.values():
                items.append(
                    {
                        "node_id": n.node_id,
                        "hostname": n.hostname,
                        "ip": n.ip,
                        "alive": n.alive,
                        "resources": dict(n.total),
                        "available": dict(n.avail),
                    }
                )
        elif kind == "serve":
            # pivot the serve metric series into one row per
            # (deployment, route): counters/gauges flatten to scalars,
            # histograms keep {sum, count, buckets} so the client side
            # (util/state.summarize_serve) can estimate percentiles and
            # batch efficiency without a second scrape
            self._merge_shard_metrics()
            prefix = "ray_tpu_serve_"
            rows: Dict[tuple, dict] = {}
            for (mname, tags), m in self.metrics.items():
                if not mname.startswith(prefix):
                    continue
                tagmap = dict(tags)
                key = (tagmap.get("deployment", ""), tagmap.get("route", ""))
                row = rows.setdefault(
                    key, {"deployment": key[0], "route": key[1]}
                )
                short = mname[len(prefix):]
                if m["type"] == "histogram":
                    row[short] = {
                        "sum": m["sum"],
                        "count": m["count"],
                        "buckets": [list(b) for b in m["buckets"]],
                    }
                else:
                    row[short] = m["value"]
            items = [rows[k] for k in sorted(rows)]
        self._reply(conn, p["req_id"], items=items)

    def _on_shutdown(self, conn, p):
        self._running = False

    def shutdown(self, timeout: float = 5.0):
        self._running = False
        # wake router via a self-connection
        try:
            from .client import connect_hub

            c = connect_hub(self.addr)
            c.close()
        except Exception:
            pass
        self._shutdown_evt.wait(timeout)
        for w in self.workers.values():
            if w.proc is not None:
                try:
                    w.proc.terminate()
                    w.proc.wait(timeout=1)
                except Exception:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
        if self._kv_store is not None:
            self._kv_store.close()
