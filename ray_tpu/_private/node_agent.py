"""Node agent: the per-host daemon of a multi-host cluster.

The analogue of the reference's raylet/NodeManager
(src/ray/raylet/node_manager.h:122, main.cc) reduced to what a TPU pod
actually needs from a per-host runtime: register the host's resources
with the hub, fork worker processes on demand, serve shm-segment reads
for cross-node object fetches, and report child deaths. Scheduling
stays centralized in the hub (single-controller, like the GCS-direct
actor-scheduling mode, gcs_actor_scheduler.cc:54) — the agent is a
thin execution arm, so there is no raylet-side state to keep consistent.

Wire: one TCP connection to the hub (protocol.py REGISTER_NODE /
SPAWN_WORKER / WORKER_EXITED / OBJ_READ / OBJ_UNLINK / KILL).

Spawned workers connect straight to the hub themselves; the agent only
owns their lifetime (terminate on KILL/SIGTERM, reap on exit).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
from typing import Dict

from . import protocol as P
from .serialization import dumps_frame, loads_frame


def _chip_coords(ntpu: int) -> Dict[int, tuple]:
    """This host's ICI topology for SLICE placement (env-derived)."""
    from .accelerators.tpu import get_chip_topology

    return get_chip_topology(ntpu) if ntpu else {}


class NodeAgent:
    def __init__(self):
        from .client import connect_hub

        self.hub_addr = os.environ["RAY_TPU_HUB_ADDR"]
        self.node_id = os.environ["RAY_TPU_NODE_ID"]
        self.session_dir = os.environ["RAY_TPU_SESSION_DIR"]
        self.hostname = os.environ.get("RAY_TPU_NODE_HOSTNAME") or socket.gethostname()
        self.ip = os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1")
        import tempfile

        self.spill_dir = os.environ.get("RAY_TPU_SPILL_DIR") or os.path.join(
            tempfile.gettempdir(),
            "ray_tpu_spill_" + os.path.basename(self.session_dir),
        )
        os.makedirs(os.path.join(self.session_dir, "objects"), exist_ok=True)
        self.children: Dict[str, subprocess.Popen] = {}
        # out-of-band object plane: this host's data-plane endpoint.
        # Peers resolve it through the hub directory and stream segment
        # bytes here directly — the hub relay (OBJ_READ) stays as the
        # fallback. TCP because cluster mode is TCP (remote peers must
        # be able to reach it); bound to this host's address.
        self.object_agent = None
        from .config import RAY_TPU_CONFIG

        if RAY_TPU_CONFIG.object_agent:
            from .object_agent import ObjectAgent

            try:
                self.object_agent = ObjectAgent(
                    os.path.join(self.session_dir, "objects"),
                    spill_dir=self.spill_dir,
                    host=os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1"),
                )
            except OSError:
                pass  # relay-only node
        # fault injection (chaos.py, "agent" scope): drop/delay/dup on
        # this agent's outbound messages — e.g.
        # drop:agent.node_heartbeat@1 is heartbeat suppression, the
        # cheap half of a partition (the hub's heartbeat-miss watchdog
        # must then declare this node dead). None = inert.
        from .chaos import engine_for

        self._chaos = engine_for("agent")
        self.conn = connect_hub(self.hub_addr)

        resources = {"CPU": float(os.environ.get("RAY_TPU_NUM_CPUS", "1"))}
        ntpu = int(os.environ.get("RAY_TPU_NUM_TPUS", "0"))
        if ntpu:
            resources["TPU"] = float(ntpu)
        resources["memory"] = float(
            os.environ.get("RAY_TPU_MEMORY", 64 * 1024**3)
        )
        custom = os.environ.get("RAY_TPU_CUSTOM_RESOURCES")
        if custom:
            resources.update({k: float(v) for k, v in json.loads(custom).items()})
        self._send(
            P.REGISTER_NODE,
            {
                "req_id": 0,
                "node_id": self.node_id,
                "hostname": self.hostname,
                "ip": self.ip,
                "session_dir": self.session_dir,
                "resources": resources,
                "tpu_chip_ids": list(range(ntpu)),
                "tpu_chip_coords": _chip_coords(ntpu),
                "max_workers": int(
                    os.environ.get("RAY_TPU_MAX_WORKERS")
                    or max(4, int(resources["CPU"]))
                ),
                "store_cap": float(
                    os.environ.get("RAY_TPU_OBJECT_STORE_MEMORY", 0)
                ),
                "object_endpoint": (
                    self.object_agent.endpoint if self.object_agent else ""
                ),
            },
        )

    def _send(self, msg_type: str, payload: dict) -> None:
        n = 1
        if self._chaos is not None:
            n = self._chaos.outbound_send(msg_type)  # 0 drop / 1 / 2 dup
            if n == 0:
                return
        blob = dumps_frame((msg_type, payload))
        for _ in range(n):
            self.conn.send_bytes(blob)

    # ------------------------------------------------------------------
    def run(self) -> None:
        import time

        signal.signal(signal.SIGTERM, lambda *a: self._shutdown())
        # same config knob the hub's head self-sampler reads, so both
        # sides of the cluster heartbeat at one cadence
        from .config import RAY_TPU_CONFIG

        hb_period = float(RAY_TPU_CONFIG.node_heartbeat_period_s)
        # the poll timeout bounds the heartbeat jitter: at the default
        # 2s period a 1s poll is fine, but a sub-second period (tests,
        # aggressive heartbeat-miss thresholds) must not be floored to
        # the 1s poll or the hub's miss watchdog sees phantom silence
        poll_t = min(1.0, hb_period) if hb_period > 0 else 1.0
        last_hb = 0.0
        try:
            while True:
                if self.conn.poll(poll_t):
                    # bounded burst drain (the hub reactor's shape): a
                    # spawn storm from the hub — now potentially fanned
                    # out by several reactor shards at once — lands as
                    # one wake + N handles instead of N one-second poll
                    # cycles. The budget keeps reaping/heartbeats live.
                    budget = 64
                    while True:
                        blob = self.conn.recv_bytes()
                        msg_type, payload = loads_frame(blob)
                        self._handle(msg_type, payload)
                        budget -= 1
                        if budget <= 0 or not self.conn.poll(0):
                            break
                self._reap()
                now = time.monotonic()
                if hb_period > 0 and now - last_hb >= hb_period:
                    last_hb = now
                    self._heartbeat()
        except (EOFError, OSError):
            pass  # hub gone: tear down
        finally:
            self._shutdown()

    def _heartbeat(self) -> None:
        """Report this host's vitals; the hub turns them into
        ray_tpu_node_* gauges (reference: raylet resource reports
        carried on heartbeats, node_manager.cc ReportResourceUsage)."""
        from .debug import proc_rss_bytes

        rss = proc_rss_bytes(os.getpid()) + sum(
            proc_rss_bytes(p.pid) for p in self.children.values()
        )
        try:
            load = os.getloadavg()[0]
        except OSError:
            load = 0.0
        self._send(
            P.NODE_HEARTBEAT,
            {
                "node_id": self.node_id,
                "rss_bytes": rss,
                "cpu_load_1m": load,
                "n_workers": len(self.children),
                "object_agent": (
                    self.object_agent.stats() if self.object_agent else None
                ),
            },
        )

    def _handle(self, msg_type: str, p) -> None:
        if msg_type == "batch":
            # hub reactor coalesces its per-peer sends (hub._send)
            for mt, pl in p:
                self._handle(mt, pl)
            return
        if msg_type == P.SPAWN_WORKER:
            env = dict(os.environ)
            env.update(p["env"])
            env["RAY_TPU_SESSION_DIR"] = self.session_dir
            env["RAY_TPU_NODE_ID"] = self.node_id
            env["RAY_TPU_NODE_HOSTNAME"] = self.hostname
            env["RAY_TPU_NODE_IP"] = self.ip
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_process"],
                env=env,
            )
            self.children[p["worker_id"]] = proc
        elif msg_type == P.OBJ_READ:
            path = os.path.join(self.session_dir, "objects", p["name"])
            if not os.path.exists(path):
                path = os.path.join(self.spill_dir, p["name"])  # spilled
            try:
                total = None
                with open(path, "rb") as f:
                    if p.get("offset") is None:
                        data = f.read()
                    else:
                        total = os.fstat(f.fileno()).st_size
                        f.seek(p["offset"])
                        data = f.read(p.get("length"))
                self._send(P.OBJ_READ_REPLY,
                           {"fetch_id": p["fetch_id"], "data": data,
                            "total": total})
            except OSError as err:
                self._send(
                    P.OBJ_READ_REPLY,
                    {"fetch_id": p["fetch_id"], "data": None, "error": str(err)},
                )
        elif msg_type == P.OBJ_UNLINK:
            for path in (
                os.path.join(self.session_dir, "objects", p["name"]),
                os.path.join(self.spill_dir, p["name"]),
            ):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        elif msg_type == P.OBJ_SPILL:
            import shutil

            os.makedirs(self.spill_dir, exist_ok=True)
            try:
                # tmpfs -> disk crosses filesystems (os.replace => EXDEV)
                shutil.move(
                    os.path.join(self.session_dir, "objects", p["name"]),
                    os.path.join(self.spill_dir, p["name"]),
                )
            except OSError:
                pass
        elif msg_type == P.OBJ_RESTORE:
            import shutil

            try:
                shutil.move(
                    os.path.join(self.spill_dir, p["name"]),
                    os.path.join(self.session_dir, "objects", p["name"]),
                )
            except OSError:
                pass
        elif msg_type == P.KILL_WORKER:
            # hub-side execute timeout / hung-worker watchdog / chaos
            # worker faults. sig="stop" is chaos worker_hang (SIGSTOP:
            # stall, socket stays open); default is SIGKILL, not
            # terminate — a SIGSTOP'd or wedged worker queues SIGTERM
            # forever (the reap loop reports the exit)
            proc = self.children.get(p.get("worker_id", ""))
            if proc is not None:
                try:
                    if p.get("sig") == "stop":
                        proc.send_signal(signal.SIGSTOP)
                    else:
                        proc.kill()
                except Exception:
                    pass
        elif msg_type == P.KILL:
            raise EOFError  # unified teardown path

    def _reap(self) -> None:
        for wid, proc in list(self.children.items()):
            code = proc.poll()
            if code is not None:
                del self.children[wid]
                try:
                    self._send(P.WORKER_EXITED, {"worker_id": wid, "code": code})
                except (OSError, BrokenPipeError):
                    pass

    def _shutdown(self) -> None:
        if self.object_agent is not None:
            self.object_agent.close()
        for proc in self.children.values():
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in self.children.values():
            try:
                proc.wait(timeout=2)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        # the session dir holds RAM-backed object segments: leaking it
        # across repeated join/terminate cycles eats /dev/shm (the
        # Cluster harness also rmtree's from the parent side; harmless
        # double-delete)
        import shutil

        shutil.rmtree(self.session_dir, ignore_errors=True)
        os._exit(0)


def main() -> None:
    NodeAgent().run()


if __name__ == "__main__":
    main()
