"""Sampling wall-clock profiler + on-demand remote stack dumps.

The reference treats live profiling as a first-class debugging surface
(`ray stack`, py-spy-backed dashboard flamegraphs — reference:
python/ray/util/check_open_ports.py's sibling tooling and
dashboard/modules/reporter's profiling endpoints). Here the runtime is
pure Python in-process threads, so a py-spy subprocess is unnecessary:
a daemon thread snapshotting ``sys._current_frames()`` at
RAY_TPU_PROFILE_HZ sees every thread of its process — client, hub,
reactor shards, workers, serve replicas — for the cost of one frame
walk per thread per tick.

Three layers, all in this module:

- **Task register** (:func:`set_task`): worker execution paths bind
  their thread to the task id they are running, so each sample is
  attributable to a task/actor call. Call sites gate on the module
  attribute ``_ACTIVE`` (one load) — profiler off means no dict
  traffic, matching the chaos/tracing inert-when-off idiom.
- **Frame classifier** (:func:`classify_stage`): buckets a sampled
  stack into the named runtime stages (serialize, frame-encode,
  reactor-poll, lock-wait, recv/send, user-code, idle, runtime) that
  decompose ``analyze_trace``'s queue_wait into CPU causes.
- **Sampler** (:class:`Sampler` / :func:`maybe_start`): folds samples
  locally into collapsed stacks keyed (thread domain, stage, task,
  stack), flushes ~1 s batches through an injected sink (clients send
  P.PROFILE_BATCH over their hub connection; the hub's own sampler
  appends to a ring its control thread drains), tracks its own
  overhead ratio, and auto-clamps the rate past the configured budget.

Default off: with RAY_TPU_PROFILE_HZ unset/0, :func:`maybe_start`
returns None having created NOTHING — no thread, no state, no wire
frames. The tier-1 zero-cost guard asserts exactly this.

:func:`dump_threads` is independent of the sampler: `ray_tpu stack`
reads ``sys._current_frames()`` at request time, profiler or not.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple

# ------------------------------------------------------------ process state
# One sampler per process; first maybe_start caller wins (in the local
# driver the hub thread and the driver client share a process — both
# call maybe_start, exactly one sampler samples every thread).
_SAMPLER: Optional["Sampler"] = None
# Gate read by task-register call sites (worker exec loop): one module
# attribute load when the profiler is off.
_ACTIVE = False
# thread ident -> task label. Plain dict, GIL-atomic store/pop — the
# sampler reads it racily by design (a sample landing on a task
# boundary attributes to either side, both true within one tick).
_TASK_REGISTER: Dict[int, str] = {}
# process-scoped label a serve replica sets to its deployment name so
# its samples read "worker:serve:<deployment>" instead of bare "worker"
_PROC_LABEL = ""


def set_task(task_id) -> None:
    """Bind the calling thread to a task id for sample attribution."""
    if isinstance(task_id, bytes):
        task_id = task_id.hex()
    _TASK_REGISTER[threading.get_ident()] = str(task_id)


def clear_task() -> None:
    _TASK_REGISTER.pop(threading.get_ident(), None)


def set_process_label(label: str) -> None:
    """Tag every future batch from this process (serve replicas pass
    their deployment name; attribution then reads
    worker:serve:<deployment>)."""
    global _PROC_LABEL
    _PROC_LABEL = str(label)


# ------------------------------------------------------- frame classifier
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STDLIB_DIR = os.path.dirname(os.__file__)

# hand-emitted wire codec (serialization.py's frame fast paths) — more
# specific than the serialize bucket, so checked first
_FRAME_ENCODE_FUNCS = frozenset((
    "dumps_frame", "loads_frame", "splice_tasks_frame", "splice_frame",
    "_emit_frame", "_splice",
))
_SERIALIZE_FILES = frozenset((
    "serialization.py", "pickle.py", "cloudpickle.py",
    "cloudpickle_fast.py", "copyreg.py",
))
_POLL_FUNCS = frozenset(("wait", "poll", "_poll", "select", "epoll"))
_SOCKET_FILES = frozenset(("socket.py", "connection.py", "ssl.py"))
_SOCKET_FUNCS = frozenset((
    "send", "sendall", "recv", "recv_into", "recv_bytes", "send_bytes",
    "_send", "_recv", "_send_bytes", "_recv_bytes", "accept",
))
_WAIT_FILES = frozenset(("threading.py", "queue.py"))
_WAIT_FUNCS = frozenset((
    "wait", "acquire", "get", "put", "join", "_wait_for_tstate_lock",
))

STAGES = (
    "serialize", "frame-encode", "reactor-poll", "lock-wait",
    "recv/send", "user-code", "idle", "runtime",
)


def _is_idle(frames: List[Tuple[str, str]]) -> bool:
    """A worker executor parked between tasks (queue.get directly under
    the dispatch loop) is idle, not lock-wait — without this the
    flamegraph of a quiet cluster reads as one giant lock stall."""
    for i in range(min(len(frames), 4)):
        fname, func = frames[i]
        if fname.rsplit("/", 1)[-1] == "queue.py" and func == "get":
            if i + 1 < len(frames):
                nfile, nfunc = frames[i + 1]
                tail = nfile.rsplit("/", 1)[-1]
                return (
                    (tail == "worker_process.py" and nfunc == "main")
                    or (tail == "replica.py")
                )
            return False
    return False


def classify_stage(frames: List[Tuple[str, str]]) -> str:
    """Bucket one sampled stack — leaf-first (filename, funcname)
    pairs — into a named runtime stage. First match walking from the
    leaf wins: the innermost recognizable activity is what the CPU (or
    the blocked syscall) was actually doing."""
    if not frames:
        return "runtime"
    idle = _is_idle(frames)
    for filename, func in frames:
        tail = filename.rsplit("/", 1)[-1]
        if func in _FRAME_ENCODE_FUNCS:
            return "frame-encode"
        if tail in _SERIALIZE_FILES:
            return "serialize"
        if tail == "selectors.py" or (
            tail == "connection.py" and func in _POLL_FUNCS
        ):
            return "reactor-poll"
        if tail in _SOCKET_FILES and func in _SOCKET_FUNCS:
            return "recv/send"
        if tail in _WAIT_FILES and func in _WAIT_FUNCS:
            return "idle" if idle else "lock-wait"
        if (
            not filename.startswith(_PKG_DIR)
            and not filename.startswith(_STDLIB_DIR)
            # <frozen importlib...> is runtime; <stdin>/<string> are
            # user code (REPL-defined functions keep their synthetic
            # filename through cloudpickle into the worker)
            and not filename.startswith("<frozen")
        ):
            return "user-code"
    return "runtime"


def classify_thread(name: str) -> str:
    """Map a thread name to its runtime domain (reader / flusher /
    reactor / shard / executor / aio / ...). Unknown names pass
    through — a user thread keeps its own name as its domain."""
    if name == "MainThread":
        return "main"
    if "hub-shard" in name:
        return "shard"
    if name == "ray-tpu-hub":
        return "reactor"
    if "reader" in name:
        return "reader"
    if "flusher" in name:
        return "flusher"
    if "profile" in name:
        return "profiler"
    if "aio" in name or "asyncio" in name:
        return "aio"
    if "dashboard" in name:
        return "dashboard"
    if "object-agent" in name or "object_agent" in name:
        return "object-agent"
    return name


def _frame_pairs(frame, limit: int = 64) -> List[Tuple[str, str]]:
    """Walk one thread's frame chain leaf-first into (filename,
    funcname) pairs — the classifier's and folder's shared input."""
    pairs: List[Tuple[str, str]] = []
    f = frame
    while f is not None and len(pairs) < limit:
        code = f.f_code
        pairs.append((code.co_filename, code.co_name))
        f = f.f_back
    return pairs


def _collapse(pairs: List[Tuple[str, str]]) -> str:
    """Root->leaf semicolon-joined folded-stack string (flamegraph
    collapsed format): ``module:func;module:func;...``."""
    parts = []
    for filename, func in reversed(pairs):
        tail = filename.rsplit("/", 1)[-1]
        if tail.endswith(".py"):
            tail = tail[:-3]
        parts.append(f"{tail}:{func}")
    return ";".join(parts)


# --------------------------------------------------------------- sampler
class Sampler:
    """Per-process sampling daemon. Folds locally, flushes through the
    injected sink every ``flush_period`` seconds, self-measures its
    overhead (sample-pass time / wall window) and halves its rate when
    the ratio exceeds ``budget`` (auto-clamp — a profiler that costs
    more than its budget silently degrades resolution, never the
    workload)."""

    def __init__(self, hz: float, kind: str, sink: Callable[[dict], None],
                 budget: float = 0.03, flush_period: float = 1.0):
        self.hz = float(hz)
        self.kind = kind
        self.sink = sink
        self.budget = float(budget)
        self.flush_period = float(flush_period)
        self.overhead = 0.0
        self.clamped = False
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name="ray-tpu-profile-sampler",
        )

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _kind(self) -> str:
        return f"{self.kind}:{_PROC_LABEL}" if _PROC_LABEL else self.kind

    def _sample_once(self, fold: Dict[tuple, int], my_ident: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == my_ident:
                continue  # never profile the profiler
            pairs = _frame_pairs(frame)
            key = (
                classify_thread(names.get(ident) or f"tid-{ident}"),
                classify_stage(pairs),
                _TASK_REGISTER.get(ident, ""),
                _collapse(pairs),
            )
            fold[key] = fold.get(key, 0) + 1

    def _loop(self) -> None:
        fold: Dict[tuple, int] = {}
        cost = 0.0
        my_ident = threading.get_ident()
        window0 = time.monotonic()
        while not self._stop.wait(1.0 / self.hz):
            t0 = time.perf_counter()
            try:
                self._sample_once(fold, my_ident)
            except Exception:
                pass  # a torn frame walk must never kill the sampler
            cost += time.perf_counter() - t0
            now = time.monotonic()
            window = now - window0
            if window >= self.flush_period:
                self.overhead = cost / window if window > 0 else 0.0
                if (
                    self.budget > 0
                    and self.overhead > self.budget
                    and self.hz > 1.0
                ):
                    # auto-clamp: halve the rate, floor at 1 Hz
                    self.hz = max(1.0, self.hz / 2.0)
                    self.clamped = True
                if fold:
                    try:
                        self.sink({
                            "pid": os.getpid(),
                            "kind": self._kind(),
                            "samples": fold,
                            "overhead": self.overhead,
                            "hz": self.hz,
                        })
                    except Exception:
                        pass  # hub going away must not kill the sampler
                    fold = {}
                cost = 0.0
                window0 = now


def maybe_start(kind: str, sink: Callable[[dict], None],
                hz: Optional[float] = None,
                budget: Optional[float] = None,
                flush_period: Optional[float] = None) -> Optional["Sampler"]:
    """Start the process-wide sampler iff the sample rate is > 0.

    Rate/budget default to the RAY_TPU_PROFILE_* env knobs (workers and
    clients inherit env from their spawner and never run config
    reload(), same as chaos_plan). First caller wins; with the rate at
    its default 0 nothing at all is created."""
    global _SAMPLER, _ACTIVE
    if _SAMPLER is not None:
        return _SAMPLER
    if hz is None:
        hz = _env_float("RAY_TPU_PROFILE_HZ", 0.0)
    if float(hz) <= 0:
        return None
    if budget is None:
        budget = _env_float("RAY_TPU_PROFILE_OVERHEAD_BUDGET", 0.03)
    if flush_period is None:
        flush_period = _env_float("RAY_TPU_PROFILE_FLUSH_PERIOD_S", 1.0)
    s = Sampler(float(hz), kind, sink, float(budget),
                max(0.05, float(flush_period)))
    _SAMPLER = s
    _ACTIVE = True
    s.start()
    return s


def stop() -> None:
    """Tear the process sampler down (tests; a stopped sampler flushes
    nothing further and the register gate goes back to inert)."""
    global _SAMPLER, _ACTIVE
    if _SAMPLER is not None:
        _SAMPLER.stop()
        _SAMPLER = None
    _ACTIVE = False
    _TASK_REGISTER.clear()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return default


# ------------------------------------------------------------ stack dumps
def dump_threads() -> List[dict]:
    """All-thread stack dump of THIS process (`ray_tpu stack` — the
    STACK_DUMP handler in clients/workers and the hub's inline answer
    for target "hub"). Reads sys._current_frames() at call time; no
    sampler involved."""
    frames = sys._current_frames()
    out = []
    for t in threading.enumerate():
        f = frames.get(t.ident)
        lines: List[str] = []
        if f is not None:
            lines = [
                ln.rstrip("\n")
                for entry in traceback.format_stack(f)
                for ln in entry.splitlines()
            ]
        out.append({
            "thread": t.name,
            "ident": t.ident,
            "daemon": t.daemon,
            "frames": lines,
        })
    return out
