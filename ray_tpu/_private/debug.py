"""Last-resort error reporting for control-plane threads.

The hub reactor, timers, and the client reader all follow the same
rule: a stray exception must cost one unit of work (a connection, a
timer tick), never the thread — but the traceback has to surface
somewhere. This is the one place that banner format lives.
"""

from __future__ import annotations

import sys
import traceback


def log_exc(prefix: str) -> None:
    """Write the active exception's traceback to stderr under the
    ``[ray_tpu]`` banner. For broad-``except`` arms where raising is
    not an option and losing the traceback is worse."""
    sys.stderr.write(f"[ray_tpu] {prefix}:\n{traceback.format_exc()}\n")


def proc_rss_bytes(pid: int) -> int:
    """Resident set size of a live process, 0 if unreadable (process
    gone, or no /proc). Shared by the hub's memory monitor and the
    hub/agent heartbeat samplers."""
    import os

    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, IndexError, ValueError):
        return 0
