"""Serialization for task args, results, and functions.

Mirrors the split the reference makes (reference:
python/ray/_private/serialization.py:122 SerializationContext):

- *functions/closures* go through cloudpickle (pickle-by-value), exported
  once per function and cached by the receiving worker (reference:
  python/ray/_private/function_manager.py:58).
- *data* goes through cloudpickle at protocol 5 with out-of-band buffers
  so numpy/jax arrays are not copied into the pickle stream. cloudpickle
  (not stdlib pickle) everywhere: importable objects serialize by
  reference at plain-pickle speed, while __main__-level functions and
  closures — which stdlib pickle would emit by reference and the worker
  could never import — serialize by value.

The wire format is a (header_bytes, [buffer, ...]) pair; buffers can be
placed into shared memory by the object store for zero-copy cross-process
transfer.

Frame codec (hub<->client<->agent framing, PR 2): every wire frame
carries a one-byte marker prefix —

- ``b"P"`` — stdlib pickle. The fast path: control frames are
  (msg_type, payload-dict) pairs of primitives/bytes, and stdlib
  pickle's C implementation serializes those ~2x faster than a
  CloudPickler round. Used by :func:`dumps_frame`.
- ``b"C"`` — cloudpickle. Used for anything that may capture user
  objects (:func:`dumps_inline` payload blobs, :func:`dumps_oob`
  headers), and as the automatic fallback when stdlib pickle raises
  on a frame (e.g. a ``__main__``-level lambda smuggled into a
  payload).

Both markers decode with ``pickle.loads`` (cloudpickle output IS
pickle bytecode); the split exists so the dump side can pick the cheap
encoder per frame. The ``__main__`` by-reference trap stays
impossible: arbitrary user values never ride a frame raw — task args
travel as ``dumps_inline`` blobs (remote_function.encode_args), values
as ``dumps_oob`` headers, functions as ``dumps_function`` blobs, and
pubsub data as ``dumps_inline`` blobs (client.publish) — all
cloudpickle-encoded *before* framing.
"""

from __future__ import annotations

import pickle
import pickletools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

PICKLE5 = 5

# frame markers (see module docstring)
MARKER_PLAIN = b"P"
MARKER_CLOUD = b"C"
_KNOWN_MARKERS = (ord("P"), ord("C"))


def dumps_oob(obj: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers. Returns (header, buffers).

    Always cloudpickle: plain pickle would serialize ``__main__``-level
    functions BY REFERENCE (module+qualname) — succeeding here and
    failing only at load time inside the worker, where ``__main__`` is
    the worker binary. cloudpickle pickles importable objects by
    reference (plain-pickle speed) and main/closure objects by value.
    """
    buffers: List[pickle.PickleBuffer] = []
    header = cloudpickle.dumps(obj, protocol=PICKLE5, buffer_callback=buffers.append)
    return b"C" + header, buffers


def loads_oob(header: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(header[1:], buffers=buffers)


class RawPayload:
    """Zero-copy carrier for one large raw buffer (bytes / bytearray /
    memoryview).

    Pickling emits the buffer OUT-OF-BAND (``pickle.PickleBuffer``), so
    a ``dumps_oob`` round produces a ~100-byte header plus the untouched
    buffer: ``put_raw`` memcpys it into the segment once, and a reader's
    ``loads_oob`` reconstructs a memoryview directly over the mapped
    bytes — the body is never copied into a pickle stream on either
    side. Plain ``bytes`` lack this property (no buffer-callback
    support in-band), which is why the serve payload codec
    (serve/_private/payloads.py) wraps them here before ``put_value``.
    The unpickled form IS the memoryview, not a RawPayload — consumers
    normalize with :func:`materialize_raw`.
    """

    __slots__ = ("view",)

    def __init__(self, data):
        view = data if isinstance(data, memoryview) else memoryview(data)
        if view.format != "B" or view.ndim != 1:
            view = view.cast("B")
        self.view = view

    @property
    def nbytes(self) -> int:
        return self.view.nbytes

    def __reduce_ex__(self, protocol):
        return (_rebuild_raw, (pickle.PickleBuffer(self.view),))


def _rebuild_raw(buf) -> memoryview:
    if isinstance(buf, pickle.PickleBuffer):
        buf = buf.raw()
    view = buf if isinstance(buf, memoryview) else memoryview(buf)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    return view


def materialize_raw(value: Any) -> Any:
    """Collapse the two shapes a fetched RawPayload can take — the
    producer-process cache hit returns the wrapper itself, a real
    deserialization returns the rebuilt memoryview — into a memoryview."""
    if isinstance(value, RawPayload):
        return value.view
    return value


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class by value (closures included)."""
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes) -> Any:
    return cloudpickle.loads(blob)


def dumps_inline(obj: Any) -> bytes:
    """One-shot serialize (no out-of-band buffers) for payload blobs
    that may capture arbitrary user objects (task args, error values,
    pubsub data). cloudpickle for the same by-reference trap as
    dumps_oob."""
    return MARKER_CLOUD + cloudpickle.dumps(obj, protocol=PICKLE5)


def dumps_frame(obj: Any) -> bytes:
    """Serialize one wire frame: stdlib pickle fast path with automatic
    cloudpickle fallback.

    Frames are (msg_type, payload) pairs whose user-facing values are
    already pre-serialized bytes blobs (module docstring), so stdlib
    pickle's C encoder handles ~every frame; anything it rejects
    (a closure/lambda smuggled into a payload) falls back to
    cloudpickle's by-value treatment instead of failing the send.
    """
    try:
        return MARKER_PLAIN + pickle.dumps(obj, protocol=PICKLE5)
    except Exception:
        return MARKER_CLOUD + cloudpickle.dumps(obj, protocol=PICKLE5)


def loads_frame(blob: bytes) -> Any:
    """Decode a frame produced by dumps_frame OR dumps_inline (both
    markers are pickle bytecode; the marker is validated so a corrupt
    or unframed blob fails loudly here, not deep inside a handler)."""
    if not blob or blob[0] not in _KNOWN_MARKERS:
        raise ValueError(
            f"bad wire frame: unknown codec marker {blob[:1]!r}"
        )
    if len(blob) > 65536:
        # memoryview spares a full copy of large frames (inline puts
        # run right up to INLINE_THRESHOLD); for small ones the plain
        # slice is cheaper than building the view
        return pickle.loads(memoryview(blob)[1:])
    return pickle.loads(blob[1:])


def loads_inline(blob: bytes) -> Any:
    return pickle.loads(blob[1:])


# ------------------------------------------------------- frame splicing
# Template-spliced SUBMIT_TASKS frames (client hot path, round 3). A
# ``.remote()`` loop re-pickles the same fn_id / resources / options
# dict on every call; here the invariant *prefix* of the frame is
# pickled ONCE into raw opcode bytes and each call contributes only a
# hand-emitted fragment for its task id, arg blob, and deps. The spliced
# stream decodes with the ordinary ``loads_frame`` — the hub cannot
# tell a spliced frame from a ``dumps_frame`` one.
#
# Splice safety: a fragment cut out of ``pickle.dumps`` output is safe
# to embed in a foreign stream iff it never READS the memo (GET /
# BINGET / LONG_BINGET) — MEMOIZE ops only append and are harmless
# pollution, and mixed framed/unframed opcode runs are legal pickle.
# ``value_fragment`` verifies that once per template build with
# ``pickletools.genops``; the per-call emitters below never touch the
# memo at all. Anything unsafe (shared references inside options, an
# unpicklable value) returns None and the caller falls back to the
# plain ``dumps_frame`` path.

_PROTO5 = b"\x80\x05"
_FRAME_LEAD = 0x95  # FRAME opcode: 8-byte LE length follows
_MEMO_READS = frozenset(("GET", "BINGET", "LONG_BINGET"))


def _op_str(s: str) -> bytes:
    """SHORT_BINUNICODE / BINUNICODE push of a str."""
    raw = s.encode("utf-8", "surrogatepass")
    if len(raw) < 256:
        return b"\x8c" + bytes((len(raw),)) + raw
    return b"X" + len(raw).to_bytes(4, "little") + raw


def _op_bytes(b: bytes) -> bytes:
    """SHORT_BINBYTES / BINBYTES push of a bytes value."""
    if len(b) < 256:
        return b"C" + bytes((len(b),)) + b
    return b"B" + len(b).to_bytes(4, "little") + b


def _op_int(i: int) -> bytes:
    """BININT1/2/4 push of an int (LONG1 outside int32)."""
    if 0 <= i < 256:
        return b"K" + bytes((i,))
    if 0 <= i < 65536:
        return b"M" + i.to_bytes(2, "little")
    if -0x80000000 <= i <= 0x7FFFFFFF:
        return b"J" + i.to_bytes(4, "little", signed=True)
    enc = pickle.encode_long(i)
    return b"\x8a" + bytes((len(enc),)) + enc


def _op_bytes_list(items: Sequence[bytes]) -> bytes:
    """Push a list of bytes values (EMPTY_LIST or MARK..APPENDS)."""
    if not items:
        return b"]"
    return b"](" + b"".join(_op_bytes(b) for b in items) + b"e"


def value_fragment(obj: Any) -> Optional[bytes]:
    """Pickle ``obj`` into a splice-safe opcode fragment (PROTO/FRAME
    header and trailing STOP stripped), or None if the result reads the
    pickle memo and therefore cannot be embedded in a foreign stream."""
    try:
        blob = pickle.dumps(obj, protocol=PICKLE5)
        for op, _arg, _pos in pickletools.genops(blob):
            if op.name in _MEMO_READS:
                return None
    except Exception:
        return None
    body = blob[2:] if blob[:2] == _PROTO5 else blob
    if body and body[0] == _FRAME_LEAD:
        body = body[9:]
    if not body.endswith(b"."):
        return None
    return body[:-1]


def submit_frame_prefix(msg_type: str, fields: Dict[str, Any]) -> Optional[bytes]:
    """Precompute the invariant prefix of a ``(msg_type, payload)``
    frame: the payload dict is left OPEN (MARK not yet consumed) so the
    per-batch close can splice variable items into the same dict. None
    if any field value is not splice-safe."""
    parts = [_PROTO5, _op_str(msg_type), b"}("]
    for k, v in fields.items():
        frag = value_fragment(v)
        if frag is None:
            return None
        parts.append(_op_str(k))
        parts.append(frag)
    return b"".join(parts)


# per-call dict keys, emitted once (task_entry_fragment is the per-call
# hot path; re-encoding constant key strings there is exactly the waste
# this module exists to remove)
_K_TASK_ID = _op_str("task_id")
_K_ARGS_KIND = _op_str("args_kind")
_K_ARGS_PAYLOAD = _op_str("args_payload")
_K_ARG_DEPS = _op_str("arg_deps")
_K_RETURN_IDS = _op_str("return_ids")
_K_TASKS = _op_str("tasks")
_K_REQ_ID = _op_str("req_id")
_K_TRACE = _op_str("trace")


# precomputed opcode runs for the dominant task_entry_fragment shape
# (short ids, inline args, no deps, one return id) — this is THE
# per-call hot path, so the constant glue between the variable values
# is emitted once at import instead of five _op_* calls per task
_LEN1 = tuple(bytes((i,)) for i in range(256))
_ENTRY_HEAD = b"}(" + _K_TASK_ID + b"C"  # + len1 + task_id
_KIND_INLINE = _K_ARGS_KIND + _op_str("inline") + _K_ARGS_PAYLOAD
# empty arg_deps straight into a single short return id: ]e bracket the
# one-element return_ids list, u closes the task dict
_TAIL_NODEPS_1RET = _K_ARG_DEPS + b"]" + _K_RETURN_IDS + b"](C"


def task_entry_fragment(
    task_id: bytes,
    args_kind: str,
    args_payload: bytes,
    arg_deps: Sequence[bytes],
    return_ids: Sequence[bytes],
) -> bytes:
    """Hand-emit one SUBMIT_TASKS per-task dict as raw opcodes. Never
    touches the memo, so it splices into any prefix."""
    lp = len(args_payload)
    if (args_kind == "inline" and lp < 256 and not arg_deps
            and len(return_ids) == 1 and len(task_id) < 256
            and len(return_ids[0]) < 256):
        # fast shape: one join over mostly-precomputed runs
        rid = return_ids[0]
        return b"".join((
            _ENTRY_HEAD, _LEN1[len(task_id)], task_id,
            _KIND_INLINE, b"C", _LEN1[lp], args_payload,
            _TAIL_NODEPS_1RET, _LEN1[len(rid)], rid, b"eu",
        ))
    return b"".join((
        b"}(",
        _K_TASK_ID, _op_bytes(task_id),
        _K_ARGS_KIND, _op_str(args_kind),
        _K_ARGS_PAYLOAD, _op_bytes(args_payload),
        _K_ARG_DEPS, _op_bytes_list(arg_deps),
        _K_RETURN_IDS, _op_bytes_list(return_ids),
        b"u",
    ))


def close_submit_frame(
    prefix: bytes,
    task_frags: Sequence[bytes],
    req_id: Optional[int] = None,
    trace: Optional[Tuple[str, str]] = None,
) -> bytes:
    """Complete a spliced SUBMIT_TASKS wire frame: prefix + tasks list
    + optional req_id/trace, closing the payload dict and the
    (msg_type, payload) tuple. Returns marker-prefixed frame bytes
    ready for ``Connection.send_bytes``."""
    parts = [prefix, _K_TASKS, b"]("]
    parts.extend(task_frags)
    parts.append(b"e")
    if req_id is not None:
        parts.append(_K_REQ_ID)
        parts.append(_op_int(req_id))
    if trace is not None:
        parts.append(_K_TRACE)
        parts.append(_op_str(trace[0]))
        parts.append(_op_str(trace[1]))
        parts.append(b"\x86")
    parts.append(b"u\x86.")
    return MARKER_PLAIN + b"".join(parts)
