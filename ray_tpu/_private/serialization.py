"""Serialization for task args, results, and functions.

Mirrors the split the reference makes (reference:
python/ray/_private/serialization.py:122 SerializationContext):

- *functions/closures* go through cloudpickle (pickle-by-value), exported
  once per function and cached by the receiving worker (reference:
  python/ray/_private/function_manager.py:58).
- *data* goes through cloudpickle at protocol 5 with out-of-band buffers
  so numpy/jax arrays are not copied into the pickle stream. cloudpickle
  (not stdlib pickle) everywhere: importable objects serialize by
  reference at plain-pickle speed, while __main__-level functions and
  closures — which stdlib pickle would emit by reference and the worker
  could never import — serialize by value.

The wire format is a (header_bytes, [buffer, ...]) pair; buffers can be
placed into shared memory by the object store for zero-copy cross-process
transfer.

Frame codec (hub<->client<->agent framing, PR 2): every wire frame
carries a one-byte marker prefix —

- ``b"P"`` — stdlib pickle. The fast path: control frames are
  (msg_type, payload-dict) pairs of primitives/bytes, and stdlib
  pickle's C implementation serializes those ~2x faster than a
  CloudPickler round. Used by :func:`dumps_frame`.
- ``b"C"`` — cloudpickle. Used for anything that may capture user
  objects (:func:`dumps_inline` payload blobs, :func:`dumps_oob`
  headers), and as the automatic fallback when stdlib pickle raises
  on a frame (e.g. a ``__main__``-level lambda smuggled into a
  payload).

Both markers decode with ``pickle.loads`` (cloudpickle output IS
pickle bytecode); the split exists so the dump side can pick the cheap
encoder per frame. The ``__main__`` by-reference trap stays
impossible: arbitrary user values never ride a frame raw — task args
travel as ``dumps_inline`` blobs (remote_function.encode_args), values
as ``dumps_oob`` headers, functions as ``dumps_function`` blobs, and
pubsub data as ``dumps_inline`` blobs (client.publish) — all
cloudpickle-encoded *before* framing.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle

PICKLE5 = 5

# frame markers (see module docstring)
MARKER_PLAIN = b"P"
MARKER_CLOUD = b"C"
_KNOWN_MARKERS = (ord("P"), ord("C"))


def dumps_oob(obj: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers. Returns (header, buffers).

    Always cloudpickle: plain pickle would serialize ``__main__``-level
    functions BY REFERENCE (module+qualname) — succeeding here and
    failing only at load time inside the worker, where ``__main__`` is
    the worker binary. cloudpickle pickles importable objects by
    reference (plain-pickle speed) and main/closure objects by value.
    """
    buffers: List[pickle.PickleBuffer] = []
    header = cloudpickle.dumps(obj, protocol=PICKLE5, buffer_callback=buffers.append)
    return b"C" + header, buffers


def loads_oob(header: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(header[1:], buffers=buffers)


class RawPayload:
    """Zero-copy carrier for one large raw buffer (bytes / bytearray /
    memoryview).

    Pickling emits the buffer OUT-OF-BAND (``pickle.PickleBuffer``), so
    a ``dumps_oob`` round produces a ~100-byte header plus the untouched
    buffer: ``put_raw`` memcpys it into the segment once, and a reader's
    ``loads_oob`` reconstructs a memoryview directly over the mapped
    bytes — the body is never copied into a pickle stream on either
    side. Plain ``bytes`` lack this property (no buffer-callback
    support in-band), which is why the serve payload codec
    (serve/_private/payloads.py) wraps them here before ``put_value``.
    The unpickled form IS the memoryview, not a RawPayload — consumers
    normalize with :func:`materialize_raw`.
    """

    __slots__ = ("view",)

    def __init__(self, data):
        view = data if isinstance(data, memoryview) else memoryview(data)
        if view.format != "B" or view.ndim != 1:
            view = view.cast("B")
        self.view = view

    @property
    def nbytes(self) -> int:
        return self.view.nbytes

    def __reduce_ex__(self, protocol):
        return (_rebuild_raw, (pickle.PickleBuffer(self.view),))


def _rebuild_raw(buf) -> memoryview:
    if isinstance(buf, pickle.PickleBuffer):
        buf = buf.raw()
    view = buf if isinstance(buf, memoryview) else memoryview(buf)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    return view


def materialize_raw(value: Any) -> Any:
    """Collapse the two shapes a fetched RawPayload can take — the
    producer-process cache hit returns the wrapper itself, a real
    deserialization returns the rebuilt memoryview — into a memoryview."""
    if isinstance(value, RawPayload):
        return value.view
    return value


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class by value (closures included)."""
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes) -> Any:
    return cloudpickle.loads(blob)


def dumps_inline(obj: Any) -> bytes:
    """One-shot serialize (no out-of-band buffers) for payload blobs
    that may capture arbitrary user objects (task args, error values,
    pubsub data). cloudpickle for the same by-reference trap as
    dumps_oob."""
    return MARKER_CLOUD + cloudpickle.dumps(obj, protocol=PICKLE5)


def dumps_frame(obj: Any) -> bytes:
    """Serialize one wire frame: stdlib pickle fast path with automatic
    cloudpickle fallback.

    Frames are (msg_type, payload) pairs whose user-facing values are
    already pre-serialized bytes blobs (module docstring), so stdlib
    pickle's C encoder handles ~every frame; anything it rejects
    (a closure/lambda smuggled into a payload) falls back to
    cloudpickle's by-value treatment instead of failing the send.
    """
    try:
        return MARKER_PLAIN + pickle.dumps(obj, protocol=PICKLE5)
    except Exception:
        return MARKER_CLOUD + cloudpickle.dumps(obj, protocol=PICKLE5)


def loads_frame(blob: bytes) -> Any:
    """Decode a frame produced by dumps_frame OR dumps_inline (both
    markers are pickle bytecode; the marker is validated so a corrupt
    or unframed blob fails loudly here, not deep inside a handler)."""
    if not blob or blob[0] not in _KNOWN_MARKERS:
        raise ValueError(
            f"bad wire frame: unknown codec marker {blob[:1]!r}"
        )
    if len(blob) > 65536:
        # memoryview spares a full copy of large frames (inline puts
        # run right up to INLINE_THRESHOLD); for small ones the plain
        # slice is cheaper than building the view
        return pickle.loads(memoryview(blob)[1:])
    return pickle.loads(blob[1:])


def loads_inline(blob: bytes) -> Any:
    return pickle.loads(blob[1:])
