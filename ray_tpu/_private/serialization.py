"""Serialization for task args, results, and functions.

Mirrors the split the reference makes (reference:
python/ray/_private/serialization.py:122 SerializationContext):

- *functions/closures* go through cloudpickle (pickle-by-value), exported
  once per function and cached by the receiving worker (reference:
  python/ray/_private/function_manager.py:58).
- *data* goes through cloudpickle at protocol 5 with out-of-band buffers
  so numpy/jax arrays are not copied into the pickle stream. cloudpickle
  (not stdlib pickle) everywhere: importable objects serialize by
  reference at plain-pickle speed, while __main__-level functions and
  closures — which stdlib pickle would emit by reference and the worker
  could never import — serialize by value.

The wire format is a (header_bytes, [buffer, ...]) pair; buffers can be
placed into shared memory by the object store for zero-copy cross-process
transfer.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle

PICKLE5 = 5


def dumps_oob(obj: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers. Returns (header, buffers).

    Always cloudpickle: plain pickle would serialize ``__main__``-level
    functions BY REFERENCE (module+qualname) — succeeding here and
    failing only at load time inside the worker, where ``__main__`` is
    the worker binary. cloudpickle pickles importable objects by
    reference (plain-pickle speed) and main/closure objects by value.
    """
    buffers: List[pickle.PickleBuffer] = []
    header = cloudpickle.dumps(obj, protocol=PICKLE5, buffer_callback=buffers.append)
    return b"C" + header, buffers


def loads_oob(header: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(header[1:], buffers=buffers)


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class by value (closures included)."""
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes) -> Any:
    return cloudpickle.loads(blob)


def dumps_inline(obj: Any) -> bytes:
    """One-shot serialize (no out-of-band buffers) for small control
    data. cloudpickle for the same by-reference trap as dumps_oob."""
    return b"C" + cloudpickle.dumps(obj, protocol=PICKLE5)


def loads_inline(blob: bytes) -> Any:
    return pickle.loads(blob[1:])
