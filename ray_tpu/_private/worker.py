"""Driver-process global runtime: init/shutdown and the public verbs.

Parity: python/ray/_private/worker.py in the reference (ray.init :1286,
ray.get :2718, ray.put :2854, ray.wait :2919, ray.kill :3099). The
driver hosts the control hub in-process (a thread) instead of spawning
gcs_server/raylet binaries — on a single TPU host there is no benefit
to extra control processes, and it makes `init()` ~instant.
"""

from __future__ import annotations

import atexit
import os
import shutil
import tempfile
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .. import exceptions
from ..object_ref import ObjectRef
from .client import CoreClient
from .hub import Hub
from .ids import ObjectID

_lock = threading.RLock()
_client: Optional[CoreClient] = None
_hub: Optional[Hub] = None
_session_dir: Optional[str] = None
_is_worker = False
_worker_runtime = None  # set by worker_process: get_runtime_context() actor ids


def _set_global_client(client: CoreClient) -> None:
    """Called by worker_process to make the API work inside tasks."""
    global _client, _is_worker
    _client = client
    _is_worker = True


def is_initialized() -> bool:
    return _client is not None


def get_client() -> CoreClient:
    if _client is None:
        init()
    return _client


def _detect_num_tpus() -> int:
    env = os.environ.get("RAY_TPU_NUM_TPUS")
    if env is not None:
        return int(env)
    from .jax_utils import probe_accelerator, tpu_env_markers

    # When the env advertises a TPU, probe even if jax was never
    # imported here (worth the subprocess); otherwise only an already-
    # imported jax is consulted — a CPU-only init() stays instant.
    # RAY_TPU_NUM_TPUS is the explicit override for marker-less hosts.
    return probe_accelerator(force=tpu_env_markers())[1]


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    num_gpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    max_workers: Optional[int] = None,
    worker_env: Optional[Dict[str, str]] = None,
    object_store_memory: Optional[float] = None,
    job_config=None,
    **kwargs,
):
    """Start the runtime (hub thread + on-demand worker pool), or — with
    ``address="tcp://host:port"`` — connect to an EXISTING cluster as a
    client (reference: Ray Client, ray.init("ray://...") through
    util/client/: no local runtime; all values travel inline through the
    control connection, large results are fetched via the object plane)."""
    global _client, _hub, _session_dir
    with _lock:
        if _client is not None:
            if ignore_reinit_error or _is_worker:
                return RuntimeContext()
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
        import sys

        if address:
            import uuid as _uuid

            scratch = os.path.join(
                tempfile.gettempdir(), f"ray_tpu_client_{_uuid.uuid4().hex[:8]}"
            )
            os.makedirs(scratch, exist_ok=True)
            _session_dir = scratch
            _client = CoreClient(
                address, scratch, role="client",
                worker_id=f"client_{os.getpid()}",
            )
            _client.inline_only = True  # no shared /dev/shm with the cluster
            _register_job_config(_client, job_config)
            if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0":
                _subscribe_worker_logs(_client)
            from . import usage

            usage.flush_pending()
            atexit.register(shutdown)
            return RuntimeContext()

        # The hub thread shares this process's GIL; a shorter switch interval
        # keeps control-plane latency low under CPU-bound driver code.
        sys.setswitchinterval(0.001)
        ncpu = num_cpus if num_cpus is not None else (os.cpu_count() or 1)
        ntpu = num_tpus if num_tpus is not None else _detect_num_tpus()
        res: Dict[str, float] = {"CPU": float(ncpu)}
        # accelerator-manager detection (reference: node resources built
        # from AcceleratorManager plugins) — explicit args still win
        from .accelerators import detect_resources

        detected = detect_resources()
        if num_tpus is not None:
            detected.pop("TPU", None)
        res.update(detected)
        if ntpu:
            res["TPU"] = float(ntpu)
        if num_gpus:
            res["GPU"] = float(num_gpus)
        res["memory"] = float(kwargs.get("_memory", 64 * 1024**3))
        if resources:
            res.update(resources)
        from .session import new_session_dir

        _session_dir = new_session_dir()
        os.makedirs(_session_dir, exist_ok=True)
        from .accelerators.tpu import get_chip_topology

        _hub = Hub(
            _session_dir,
            res,
            max_workers=max_workers,
            tpu_chip_ids=list(range(int(ntpu))) if ntpu else [],
            tpu_chip_coords=get_chip_topology(int(ntpu)) if ntpu else {},
            worker_env=worker_env,
            # cluster mode: listen on TCP so node agents on other hosts
            # (or simulated hosts in tests) can register
            tcp=bool(kwargs.get("_tcp_hub") or os.environ.get("RAY_TPU_TCP_HUB")),
            host=kwargs.get("_hub_host", "127.0.0.1"),
            port=int(kwargs.get("_hub_port", 0)),
            kv_store_path=kwargs.get("_kv_store_path"),
            object_store_memory=object_store_memory,
        )
        _hub.start()
        _client = CoreClient(_hub.addr, _session_dir, role="driver", worker_id="driver")
        _client.start_prewarm(store_cap=_hub.nodes["node0"].store_cap)
        _register_job_config(_client, job_config)
        if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0":
            _subscribe_worker_logs(_client)
        from . import usage

        usage.flush_pending()
        atexit.register(shutdown)
        return RuntimeContext()


def _register_job_config(client: CoreClient, job_config) -> None:
    """Register the driver's multi-tenant scheduling identity with the
    hub (fairsched): explicit JobConfig wins; otherwise `job submit`'s
    RAY_TPU_JOB_* env handoff applies; otherwise stay unregistered (the
    policy engine stays inert for plain single-tenant sessions)."""
    from ..job_config import JobConfig

    if job_config is None:
        job_config = JobConfig.from_env()
    if job_config is None:
        return
    if not isinstance(job_config, JobConfig):
        raise TypeError(
            f"init(job_config=...) expects a ray_tpu.JobConfig, got "
            f"{type(job_config)}"
        )
    client.register_job(
        job_config.job_id, job_config.tenant, job_config.priority,
        job_config.quota,
    )


def _subscribe_worker_logs(client: CoreClient) -> None:
    """Print worker stdout/stderr on the driver with a worker prefix
    (reference: the (fn pid=...) lines ray drivers show)."""
    import sys as _sys

    def on_log(rec):
        stream = _sys.stderr if rec.get("stream") == "stderr" else _sys.stdout
        for line in rec.get("lines", []):
            print(f"(worker pid={rec.get('pid')}) {line}", file=stream)

    client.subscribe("__logs__", on_log)
    from ..experimental import tqdm_ray

    tqdm_ray._driver_subscribe(client)


def shutdown() -> None:
    global _client, _hub, _session_dir
    with _lock:
        if _is_worker:
            return
        # the driver-process sampler (started by the client or the
        # in-process hub) must die with the cluster, or a later init()
        # in the same interpreter would profile into a dead sink
        from . import profiling as _profiling

        _profiling.stop()
        if _client is not None:
            _client.close()
            _client = None
        if _hub is not None:
            _hub.shutdown()
            _hub = None
        if _session_dir is not None:
            shutil.rmtree(_session_dir, ignore_errors=True)
            import tempfile

            shutil.rmtree(
                os.path.join(
                    tempfile.gettempdir(),
                    "ray_tpu_spill_" + os.path.basename(_session_dir),
                ),
                ignore_errors=True,
            )
            _session_dir = None
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


class RuntimeContext:
    """Returned by init(); mirrors ray's RayContext/RuntimeContext."""

    @property
    def address_info(self) -> dict:
        return {"session_dir": _session_dir, "address": _hub.addr if _hub else None}

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()

    def _repr_html_(self):
        # Jupyter card (reference: python/ray/widgets context repr).
        from .. import widgets

        res = cluster_resources()
        return widgets.card_html(
            "ray_tpu cluster",
            {
                "address": self.address_info["address"],
                "nodes": len(nodes()),
                "CPU": res.get("CPU", 0),
                "TPU": res.get("TPU", 0),
                "memory": f"{res.get('memory', 0) / 1024**3:.1f} GiB",
            },
        )


# --------------------------------------------------------------------- verbs
def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed.")
    client = get_client()
    oid = client.put_value(value)
    return ObjectRef(oid, _owned=True)


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    client = get_client()
    if isinstance(refs, ObjectRef):
        return client.get([refs._id], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list of ObjectRefs, got {type(refs)}")
    if not refs:
        return []
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list elements must be ObjectRefs, got {type(r)}")
    return client.get([r._id for r in refs], timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns <= 0:
        raise ValueError("num_returns must be > 0")
    client = get_client()
    # position-based mapping: the wait() pop-loop shape re-calls this
    # with ~the same 1k refs per pop, so a per-call {id: ref} dict build
    # was the dominant client-side cost of the drain (O(n^2) overall);
    # _bin is the construction-time cached raw id (one slot load/ref)
    ready_pos, not_ready_pos = client.wait_pos(
        [r._bin for r in refs], num_returns, timeout
    )
    return [refs[i] for i in ready_pos], [refs[i] for i in not_ready_pos]


def kill(actor, *, no_restart: bool = True) -> None:
    from ..actor import ActorHandle

    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    get_client().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    get_client().cancel(ref._id, force=force)


def free(refs: Sequence[ObjectRef]) -> None:
    get_client().free([r._id for r in refs])


def get_actor(name: str, namespace: Optional[str] = None):
    from ..actor import ActorHandle
    from .ids import ActorID

    aid = get_client().get_named_actor(name, namespace)
    if aid is None:
        raise ValueError(f"Failed to look up actor with name '{name}'")
    return ActorHandle(ActorID(aid))


def available_resources() -> Dict[str, float]:
    return get_client().cluster_resources(available=True)


def cluster_resources() -> Dict[str, float]:
    return get_client().cluster_resources(available=False)


def nodes() -> List[dict]:
    return get_client().list_state("nodes")


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace task timeline (reference: ray.timeline) — open the
    returned/saved JSON in chrome://tracing or Perfetto."""
    events = get_client().list_state("timeline")
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(events, f)
    return events
