"""Session-directory policy, shared by every runtime entrypoint
(driver init, Cluster harness, CLI node join): RAM-backed /dev/shm when
available (the object store mmaps segments out of it), RAY_TPU_TMPDIR
to override."""

from __future__ import annotations

import os
import tempfile
import uuid


def session_base() -> str:
    return os.environ.get("RAY_TPU_TMPDIR") or (
        "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    )


def new_session_dir(prefix: str = "ray_tpu") -> str:
    """Unique session path under the base (not created)."""
    return os.path.join(session_base(), f"{prefix}_{uuid.uuid4().hex[:8]}")
