"""Core client: the per-process endpoint talking to the control hub.

This is the analogue of the reference's CoreWorker (reference:
src/ray/core_worker/core_worker.h:166) — one instance per driver or
worker process. It owns:
  - the hub connection + a reader thread that demultiplexes inbound
    messages (task assignments vs request replies),
  - the local view of the shm object store,
  - an inline-object cache (objects are immutable, so caching is safe).

Both the driver and workers use this same class; workers additionally
run an executor loop (worker_process.py) fed from `task_queue`.

Submit templates and auto-batching (client hot path, round 3): a plain
``.remote()`` call no longer builds or pickles a payload dict. The
RemoteFunction's template caches the invariant frame PREFIX — fn_id,
canonical resources, job-stamped scheduling options, the pipeline
flag — as raw pickle opcodes (serialization.submit_frame_prefix), and
``submit_batched`` splices only the per-call task id, arg blob, and
deps (serialization.task_entry_fragment) into a pending SUBMIT_TASKS
frame. Calls to the same template within
``submit_autobatch_window_us`` coalesce into ONE bulk frame, drained
by the flusher timer, by capacity (_AB_MAX), or by ANY other outbound
message — so per-connection FIFO holds against interleaved singles,
actor calls, and puts. ObjectRefs return synchronously before the
flush; delivery rides the same _unacked_bulk retransmit + hub
per-task dedup contract as submit_many. A drain that catches exactly
one buffered call degrades to the classic SUBMIT_TASK frame (same hub
handler as window=0, no bulk ack machinery), so sync round trips
don't pay the batch tax. The window only delays the wire flush, never
the caller.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import queue
import random
import threading
import time
from concurrent.futures import Future
from multiprocessing.connection import Client as MpClient
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import exceptions
from . import protocol as P
from .debug import log_exc
from .ids import ActorID, ObjectID, TaskID, id_pair, id_slab
from .object_store import INLINE_THRESHOLD, ShmObjectStore
from .serialization import (
    close_submit_frame,
    dumps_frame,
    dumps_inline,
    loads_frame,
    loads_inline,
    task_entry_fragment,
)


# Per-CALL job identity override for worker processes: an actor with
# max_concurrency > 1 serves callers from different tenants at once, so
# identity must live in the execution context (one per pool thread /
# asyncio task), never in shared CoreClient fields — or caller A's
# nested submits get stamped with caller B's tenant and quota.
# worker_process._adopt_job_identity sets it; _stamp_job reads it first.
from contextvars import ContextVar

_job_identity: ContextVar = ContextVar("ray_tpu_job_identity", default=None)


def connect_hub(addr: str):
    """Dial the hub: "tcp://host:port" (cluster mode) or an AF_UNIX path."""
    if addr.startswith("tcp://"):
        host, port = addr[6:].rsplit(":", 1)
        return MpClient((host, int(port)), family="AF_INET")
    return MpClient(addr, family="AF_UNIX")


class CoreClient:
    def __init__(self, hub_addr: str, session_dir: str, role: str, worker_id: str):
        self.role = role
        self.worker_id = worker_id
        self.session_dir = session_dir
        self.node_id = os.environ.get("RAY_TPU_NODE_ID", "node0")
        # effective hostname for same-host transfer decisions: the
        # simulated-cluster harness fakes per-node hostnames, so two
        # "nodes" on one machine still exercise the socket path
        import socket as _socket

        self.hostname = (
            os.environ.get("RAY_TPU_NODE_HOSTNAME") or _socket.gethostname()
        )
        self.store = ShmObjectStore(session_dir)
        self.conn = connect_hub(hub_addr)
        # fault injection (chaos.py): this process's scope of the
        # cluster chaos plan — outbound message drop/delay/dup. None
        # (the default) keeps the send paths at one attribute load.
        from . import chaos as _chaos_mod

        self._chaos = _chaos_mod.engine_for(
            "worker" if role == "worker" else "client"
        )
        # retransmit backoff knobs from the config table
        # (request_retry_period_s / request_retry_max_s env or .set()
        # overrides). Instance attrs shadow the class defaults only on
        # an explicit non-default override, so tests can still
        # monkeypatch the class attributes. period <= 0 = retransmit
        # OFF (requests wait on their first send), matching the repo's
        # 0-disables convention.
        from .config import RAY_TPU_CONFIG as _cfg
        from .config import _DEFAULTS as _cfg_defaults

        try:
            stock = float(_cfg_defaults["request_retry_period_s"])
            base = float(_cfg.get("request_retry_period_s", stock))
            if base != stock:
                self._RETRY_PERIOD_S = base
            stock = float(_cfg_defaults["request_retry_max_s"])
            cap = float(_cfg.get("request_retry_max_s", stock))
            if cap != stock:
                self._RETRY_MAX_S = cap
        except (TypeError, ValueError, KeyError):
            pass  # malformed override: keep the defaults
        self._send_lock = threading.Lock()
        self._send_buf: List[tuple] = []
        self._buf_evt = threading.Event()
        # adaptive outbound coalescing (mirrors the hub's outbox
        # batching): the inline-flush threshold starts small so a
        # trickle of messages drains promptly, widens ×2 each time a
        # burst fills the window (fewer syscalls per message while the
        # producer is outrunning the drain), and decays when timer
        # flushes see small batches. _buf_cost tracks payload bytes for
        # size-aware flushing — a few large puts must not wait out the
        # message-count window.
        self._coalesce_msgs = 32
        self._buf_cost = 0
        # >0 while inside batch_window(): count-based flushes are held
        # so a caller-visible burst (ActorPool.map) leaves as few
        # frames as possible; the byte ceiling still applies.
        self._window_depth = 0
        # transparent auto-batching (see module docstring): spliced
        # task fragments pending under _send_lock, keyed by the
        # template prefix OBJECT (same template+identity reuses the
        # same cached bytes, so `is` is the batch key) and the trace
        # context of the calls. Drained by _drain_autobatch_locked.
        try:
            window_us = int(_cfg.get("submit_autobatch_window_us", 300))
        except (TypeError, ValueError):
            window_us = 300
        self._ab_window_s = max(0.0, window_us / 1e6)
        self._ab_prefix: Optional[bytes] = None
        self._ab_base: Optional[dict] = None
        self._ab_trace: Optional[tuple] = None
        self._ab_frags: List[bytes] = []
        # singleton fast path: the (task_id, kind, payload, deps, rid)
        # of the FIRST buffered call, kept only while it is alone — a
        # one-call drain degrades to the classic SUBMIT_TASK frame and
        # skips the bulk ack machinery (see _drain_autobatch_locked)
        self._ab_single: Optional[tuple] = None
        # bulk-submit ack tracking: req_id -> [future, payload,
        # next_resend_t, backoff]. SUBMIT_TASKS is fire-and-forget for
        # the caller, so the flusher thread owns the retransmit
        # schedule (see _scan_unacked); the hub's per-task dedup makes
        # replays safe. FIFO-bounded.
        self._unacked_bulk: Dict[int, list] = {}
        # registration epoch: RemoteFunction memoizes its export
        # against this value, so a reconnect (shutdown + re-init = a
        # NEW CoreClient with a fresh epoch) naturally invalidates
        # every cached registration
        self.client_epoch = next(CoreClient._EPOCH_COUNTER)
        # ownership-GC release ids, appended from ObjectRef.__del__.
        # __del__ can run at ANY allocation point — including while THIS
        # thread already holds _send_lock (GC during dumps_inline) — so
        # the only safe operation there is a plain list.append (GIL-
        # atomic, lock-free). The flusher thread drains it.
        self._release_buf: List[bytes] = []
        self._req_counter = itertools.count()
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._obj_cache: Dict[bytes, Any] = {}
        self._obj_cache_lock = threading.Lock()
        # object ids known ready (from wait replies); insertion-ordered
        # for FIFO bounding. Cleared per-id by free().
        self._known_ready: Dict[bytes, bool] = {}
        self._seen_fns: Dict[str, Any] = {}
        self.task_queue: "queue.Queue" = queue.Queue()
        self.cancelled_tasks: set = set()  # task_ids to drop at dequeue
        # client mode (ray_tpu.init(address=...)): no shared shm with
        # the cluster — small puts travel inline through the hub
        # connection, large ones chunk-stream into the head-node store
        # (encode_value / _fetch_segment_chunked)
        self.inline_only = False
        # ---- out-of-band object plane (object_agent.py): resolve an
        # object's location once through the hub directory, then move
        # the bytes peer<->peer over the owner node's object-agent
        # endpoint. Any direct-path error falls back to the hub relay.
        self._direct_enabled = os.environ.get(
            "RAY_TPU_OBJECT_DIRECT", "1"
        ).lower() not in ("0", "false", "no")
        # oid -> RESOLVE_OBJECT reply; invalidated by the __obj_freed__
        # and __node_down__ pubsub channels, FIFO-bounded like
        # _known_ready (insertion-ordered dict)
        self._resolve_cache: Dict[bytes, dict] = {}
        # endpoint -> [idle connection, ...]; a transfer checks a
        # connection out for its whole duration (the agent serves one
        # verb at a time per connection)
        self._agent_pool: Dict[str, List[Any]] = {}
        self._agent_pool_lock = threading.Lock()
        # head node's object-agent endpoint for direct puts:
        # None = not resolved yet, "" = unavailable (stay on the relay)
        self._head_agent_endpoint: Optional[str] = None
        # ---- readiness push: wait() subscribes once per unknown ref
        # set; the hub pushes ready ids as tasks finish (P.READY_PUSH),
        # the reader thread records them in _known_ready and pokes this
        # event to re-scan any parked wait()
        self._ready_push = os.environ.get(
            "RAY_TPU_READY_PUSH", "1"
        ).lower() not in ("0", "false", "no")
        self._ready_evt = threading.Event()
        # ids this client has already registered for push (cross-call
        # memo): a pop-loop's dry calls must not re-send the same 1k-id
        # subscription per push batch. Entries leave when the push
        # arrives (_on_ready_push) or on free; a stalled wait clears
        # its ids to force a re-sync (_wait_push retry period).
        self._ready_subscribed: set = set()
        # ---- runtime tracing (util/tracing.py): head-sampling rate for
        # this process's API calls. 0 (the default) keeps the hot paths
        # nearly untouched: _tracing_live() gates all tracing work
        # behind one attribute load + one contextvar read, and no
        # "trace" field ever enters a payload.
        from ..util import tracing as _tracing

        self._trace_rate = _tracing.runtime_sample_rate()
        self._trace_on = self._trace_rate > 0.0
        # pre-bound span-record send path: the sampled hot path builds
        # its record inline and calls these bound symbols instead of
        # re-importing util.tracing and re-reading os.getpid() per span
        # (the tracing_overhead bench row measures exactly this loop)
        self._pid = os.getpid()
        self._wall_at = _tracing.wall_at
        from .ids import span_id_hex as _span_id_hex

        self._span_id_hex = _span_id_hex
        # ambient-context probe, bound once: even with THIS process's
        # sampling off, a live trace context (a traced task executing
        # here while only the submitting driver samples — the hub and
        # worker span paths are payload-driven) must keep stitching
        self._trace_ctx = _tracing.current_context
        # return-object id -> (trace_id, submit_span_id) for sampled
        # submits, so the get() that collects a traced task's result
        # joins its trace. FIFO-bounded like _resolve_cache.
        self._trace_refs: Dict[bytes, tuple] = {}
        # multi-tenant scheduling identity (set by register_job): every
        # submit/PG-create from this client is stamped with it so the
        # hub's fairsched engine can order/quota/preempt per tenant
        self.job_id: Optional[str] = None
        self.tenant: Optional[str] = None
        self.priority: int = 0
        # pubsub: channel -> callback(data); callbacks run on the reader
        # thread, so keep them light (print/enqueue)
        self.subscriptions: Dict[str, Any] = {}
        self._closed = False
        # inbound dispatch table (the hub-side _handlers symmetric):
        # resolved once here instead of a per-message if/elif chain on
        # the reader thread
        self._inbound_handlers = {
            P.REPLY: self._on_reply,
            P.PUBSUB_MSG: self._on_pubsub_msg,
            P.CANCEL_TASK: self._on_cancel_task,
            P.READY_PUSH: self._on_ready_push,
            P.STACK_DUMP: self._on_stack_dump,
        }
        self.send(P.HELLO, {"role": role, "worker_id": worker_id,
                            "pid": os.getpid(), "node_id": self.node_id})
        # shm frees anywhere in the cluster invalidate the local wait()
        # readiness cache (otherwise a freed object reports ready here
        # indefinitely; the follow-up get would raise ObjectLostError)
        self.subscriptions["__obj_freed__"] = self._on_objs_freed
        self.send(P.SUBSCRIBE, {"channel": "__obj_freed__"})
        # node loss invalidates cached object locations (stale-endpoint
        # reads must fail over to re-resolve / hub relay, never hang on
        # a dead host)
        self.subscriptions["__node_down__"] = self._on_node_down
        self.send(P.SUBSCRIBE, {"channel": "__node_down__"})
        self._reader = threading.Thread(target=self._read_loop, daemon=True, name="core-client-reader")
        self._reader.start()

        self._flusher = threading.Thread(target=self._flush_loop, daemon=True, name="core-client-flusher")
        self._flusher.start()

        # sampling profiler (profiling.py): with RAY_TPU_PROFILE_HZ at
        # its default 0 this creates NOTHING — no thread, no wire
        # frames (the tier-1 zero-cost guard asserts it). Batches ride
        # the buffered async channel like metric records. In the local
        # driver the hub thread may already own the process sampler;
        # first caller wins either way.
        from . import profiling as _profiling

        _profiling.maybe_start(role, self._profile_sink)

    def start_prewarm(self, store_cap: float = 0.0) -> None:
        """Kick the background warm-pool prewarm (driver only; see
        object_store.prewarm). Disabled when the node runs a bounded
        object store — pool files live outside the cap's accounting,
        and a capped deployment is memory-constrained by definition."""
        from .config import RAY_TPU_CONFIG

        nbytes = int(os.environ.get(
            "RAY_TPU_SEGMENT_PREWARM_BYTES",
            RAY_TPU_CONFIG.segment_prewarm_bytes,
        ))
        if nbytes > 0 and store_cap <= 0 and not self.inline_only:
            threading.Thread(
                target=self.store.prewarm, args=(nbytes,),
                daemon=True, name="segment-prewarm",
            ).start()

    # ------------------------------------------------------------------ wire
    #
    # Two send paths: `send` (immediate, flushes any buffered messages first
    # so total order is preserved) and `send_async` (buffered). Buffering
    # coalesces submit storms into one syscall + one hub wakeup per batch —
    # this matters because the hub thread shares the driver's GIL; without
    # batching every message pays a GIL handoff (~sys.getswitchinterval()).
    def send(self, msg_type: str, payload: dict) -> None:
        if self._chaos is not None:
            # 0 = injected drop (the retransmit layer must recover),
            # 2 = duplicate delivery (hub dedup/idempotency must hold)
            n = self._chaos.outbound_send(msg_type)
            if n == 0:
                return
            if n == 2:
                self._send_one(msg_type, payload)  # the duplicate
        self._send_one(msg_type, payload)

    def _send_one(self, msg_type: str, payload: dict) -> None:
        with self._send_lock:
            if self._ab_frags:
                # FIFO: the pending auto-batch predates this message
                self._drain_autobatch_locked()
            if self._send_buf:
                buf, self._send_buf = self._send_buf, []
                self._buf_cost = 0
                buf.append((msg_type, payload))
                self.conn.send_bytes(dumps_frame(("batch", buf)))
            else:
                self.conn.send_bytes(dumps_frame((msg_type, payload)))

    def send_async(self, msg_type: str, payload: dict,
                   cost: int = 0) -> None:
        """Buffered send. ``cost`` is the caller's estimate of the
        payload's wire size when it knows it (put_value passes the
        encoded value size); the buffer flushes early once accumulated
        cost crosses _COALESCE_MAX_BYTES, so big payloads don't sit
        out the message-count window."""
        dup = False
        if self._chaos is not None:
            k = self._chaos.outbound_send(msg_type)
            if k == 0:
                return
            dup = k == 2
        with self._send_lock:
            if self._ab_frags:
                # FIFO: older auto-batched submits leave first
                self._drain_autobatch_locked()
            was_empty = not self._send_buf
            self._send_buf.append((msg_type, payload))
            if dup:
                # duplicate appended under the SAME acquisition so the
                # buffer-empty wake below still fires for this batch
                self._send_buf.append((msg_type, payload))
            self._buf_cost += cost
            if ((len(self._send_buf) >= self._coalesce_msgs
                    and self._window_depth == 0)
                    or self._buf_cost >= self._COALESCE_MAX_BYTES):
                buf, self._send_buf = self._send_buf, []
                self._buf_cost = 0
                if len(buf) >= self._coalesce_msgs:
                    # the producer filled the window before the flusher
                    # woke: widen it so a sustained burst pays fewer
                    # syscalls (and fewer hub wakeups) per message
                    self._coalesce_msgs = min(
                        self._coalesce_msgs * 2, self._COALESCE_CEIL
                    )
                self.conn.send_bytes(dumps_frame(("batch", buf)))
                return
        if was_empty:
            self._buf_evt.set()

    def flush(self) -> None:
        with self._send_lock:
            if self._ab_frags:
                # drain BEFORE the release buffer: an owner-GC release
                # must never overtake the submit that referenced the id
                self._drain_autobatch_locked()
            if self._release_buf:
                # swap-then-drain: concurrent __del__ appends land either
                # in the drained list (sent now) or the fresh one (next
                # flush) — nothing is lost, no lock needed on their side
                drained = self._release_buf
                self._release_buf = []
                self._send_buf.append(
                    (P.RELEASE_OWNED, {"object_ids": drained})
                )
            if self._send_buf:
                buf, self._send_buf = self._send_buf, []
                self._buf_cost = 0
                self.conn.send_bytes(dumps_frame(("batch", buf)))
                if len(buf) * 4 <= self._coalesce_msgs:
                    # a timer/explicit drain caught a small batch: the
                    # burst is over — decay the window so the next
                    # trickle of messages flushes promptly again
                    self._coalesce_msgs = max(
                        self._COALESCE_FLOOR, self._coalesce_msgs // 2
                    )

    @contextlib.contextmanager
    def batch_window(self):
        """Hold count-based coalescing flushes while a caller-visible
        burst is produced (ActorPool.map submits N actor tasks that
        cannot ride a SUBMIT_TASKS frame); on exit the whole burst is
        drained in one flush. The byte ceiling still flushes mid-window
        so a burst of large payloads can't buffer unboundedly. Safe to
        nest; the background flusher may still drain on its timer, which
        only costs an extra frame, never reorders (per-conn FIFO)."""
        with self._send_lock:
            self._window_depth += 1
        try:
            yield
        finally:
            with self._send_lock:
                self._window_depth -= 1
            self.flush()

    def submit_batched(self, prefix: bytes, base: dict, args_kind: str,
                       args_payload: bytes, arg_deps: List[bytes],
                       trace_ctx: Optional[tuple] = None) -> bytes:
        """One plain ``.remote()`` call riding the auto-batch window:
        splice a hand-emitted task fragment under the template's frame
        prefix and return the return-object id immediately. The frame
        ships on the next drain — flusher timer (_ab_window_s), the
        _AB_MAX capacity bound, or any other outbound message (FIFO).
        A different template or trace context drains the pending batch
        first, so one frame only ever carries one template's calls."""
        tid, rid = id_pair()
        frag = task_entry_fragment(tid, args_kind, args_payload,
                                   arg_deps, (rid,))
        if trace_ctx is not None:
            # outside _send_lock (takes _obj_cache_lock); remembered
            # against the ambient context — the batch span minted at
            # drain time is this call's sibling, not known yet
            self._trace_remember((rid,), trace_ctx)
        first = False
        with self._send_lock:
            if self._ab_frags and (self._ab_prefix is not prefix
                                   or self._ab_trace != trace_ctx):
                self._drain_autobatch_locked()
            self._ab_prefix = prefix
            self._ab_base = base
            self._ab_trace = trace_ctx
            self._ab_frags.append(frag)
            if len(self._ab_frags) == 1:
                self._ab_single = (tid, args_kind, args_payload,
                                   arg_deps, rid)
            else:
                self._ab_single = None
            if len(self._ab_frags) >= self._AB_MAX:
                self._drain_autobatch_locked()
            else:
                first = len(self._ab_frags) == 1
        if first:
            # wake the flusher so the window countdown starts now
            self._buf_evt.set()
        return rid

    def _drain_autobatch_locked(self) -> None:
        """Ship the pending auto-batch as ONE SUBMIT_TASKS frame.
        _send_lock is HELD: no send()/send_async()/flush() calls from
        here (plain Lock — re-entry deadlocks); span records append
        straight onto _send_buf. Any already-buffered messages are
        older than the batch and flush FIRST (per-conn FIFO)."""
        frags = self._ab_frags
        if not frags:
            return
        # the *_locked contract: every caller already holds _send_lock
        self._ab_frags = []  # graftlint: disable=GL001
        prefix = self._ab_prefix
        base = self._ab_base
        single = self._ab_single if len(frags) == 1 else None
        tr = self._ab_trace
        self._ab_prefix = None  # graftlint: disable=GL001
        self._ab_base = None  # graftlint: disable=GL001
        self._ab_single = None  # graftlint: disable=GL001
        self._ab_trace = None  # graftlint: disable=GL001
        t0 = time.monotonic()
        if single is not None and base is not None and tr is None:
            # a lone call in the window degrades to the CLASSIC
            # single-task frame: same hub handler and chaos surface as
            # the window=0 path, no req_id/ack/retransmit bookkeeping —
            # a sync .remote()+get() round trip must not pay the bulk
            # ack tax for a batch of one
            tid, kind, blob, deps, rid = single
            frame = dumps_frame((P.SUBMIT_TASK, {
                "task_id": tid,
                "fn_id": base["fn_id"],
                "args_kind": kind,
                "args_payload": blob,
                "arg_deps": deps,
                "return_ids": [rid],
                "resources": base["resources"],
                "options": base["options"],
            }))
            if self._send_buf:
                buf, self._send_buf = self._send_buf, []
                self._buf_cost = 0  # graftlint: disable=GL001 — _send_lock held (caller)
                self.conn.send_bytes(dumps_frame(("batch", buf)))
            if self._chaos is not None:
                n = self._chaos.outbound_send(P.SUBMIT_TASK)
                if n == 0:
                    return
                if n == 2:
                    self.conn.send_bytes(frame)
            self.conn.send_bytes(frame)
            return
        req_id = None
        fut: Optional[Future] = None
        if self._RETRY_PERIOD_S > 0:
            req_id = next(self._req_counter)
            fut = Future()
            with self._pending_lock:
                self._pending[req_id] = fut
        span_id = self._span_id_hex() if tr is not None else None
        frame = close_submit_frame(
            prefix, frags, req_id=req_id,
            trace=(tr[0], span_id) if tr is not None else None,
        )
        if fut is not None:
            wait_s, nxt = self._retry_delay(self._RETRY_PERIOD_S)
            while len(self._unacked_bulk) >= 256:
                # FIFO bound, as in submit_many: eviction only loses
                # retransmit coverage, the ack still resolves the future
                self._unacked_bulk.pop(
                    next(iter(self._unacked_bulk)), None)
            self._unacked_bulk[req_id] = [
                fut, frame, time.monotonic() + wait_s, nxt,
            ]
        if self._send_buf:
            buf, self._send_buf = self._send_buf, []
            self._buf_cost = 0  # graftlint: disable=GL001 — _send_lock held (caller)
            self.conn.send_bytes(dumps_frame(("batch", buf)))
        send = True
        if self._chaos is not None:
            n = self._chaos.outbound_send(P.SUBMIT_TASKS)
            if n == 0:
                send = False  # injected drop: the retransmit entry recovers
            elif n == 2:
                self.conn.send_bytes(frame)
        if send:
            self.conn.send_bytes(frame)
        if tr is not None:
            # ONE client.submit span per drained batch (the submit_many
            # shape); buffered directly — send_async would re-lock
            rec = self._span_rec(
                "client.submit", "submit", tr[0], span_id, tr[1],
                t0, time.monotonic(), n=len(frags),
            )
            self._send_buf.append((P.SPAN_RECORD, rec))  # graftlint: disable=GL001

    def _resend_raw(self, frame: bytes) -> None:
        """Retransmit a pre-encoded SUBMIT_TASKS frame (flusher
        thread, _scan_unacked). Replays carry no FIFO obligation — the
        original send established order — but chaos still sees a
        logical submit_tasks send."""
        if self._chaos is not None:
            n = self._chaos.outbound_send(P.SUBMIT_TASKS)
            if n == 0:
                return
            if n == 2:
                with self._send_lock:
                    self.conn.send_bytes(frame)
        with self._send_lock:
            self.conn.send_bytes(frame)

    def _flush_loop(self) -> None:
        # Catches stray buffered messages right after a burst ends
        # (send latency is event-driven: send_async sets _buf_evt on the
        # first buffered message). The wait timeout doubles as the drain
        # cadence for the lock-free release buffer (__del__ can't signal
        # the event: Event.set takes a lock, and __del__ may preempt a
        # thread that already holds it) — 50ms while releases are
        # flowing, backed off to 250ms when idle so a big cluster of
        # idle workers doesn't burn the core with timer wakeups.
        while not self._closed:
            timeout = 0.05 if self._release_buf else 0.25
            fired = self._buf_evt.wait(timeout=timeout)
            self._buf_evt.clear()
            if fired:
                if self._ab_frags:
                    # an auto-batch window is open: let the burst
                    # accumulate for its full window before draining
                    time.sleep(self._ab_window_s)
                elif len(self._send_buf) >= 8:
                    # a burst is mid-flight: one scheduler quantum lets
                    # the producer coalesce more before we drain. Below
                    # that, the old unconditional nap only ADDED latency
                    # to a lone urgent message — skip it.
                    time.sleep(0.0005)
            try:
                self._scan_unacked()
                self.flush()
            except (OSError, BrokenPipeError):
                return

    def _scan_unacked(self) -> None:
        """Retransmit bulk submits whose ack never came (flusher
        thread). A SUBMIT_TASKS frame dropped on the wire would
        otherwise lose N tasks silently — the hub acks each batch via
        REPLY(req_id), and any batch still unacked past its jittered
        backoff deadline is re-sent whole (per-task dedup in
        _on_submit_tasks makes the replay idempotent)."""
        if not self._unacked_bulk:
            return
        now = time.monotonic()
        acked = None
        for req_id, entry in list(self._unacked_bulk.items()):
            if entry[0].done():
                if acked is None:
                    acked = []
                acked.append(req_id)
            elif now >= entry[2]:
                wait_s, entry[3] = self._retry_delay(entry[3])
                entry[2] = now + wait_s
                if type(entry[1]) is bytes:
                    # auto-batched entry: the spliced frame was kept
                    # verbatim — replay it raw (no re-encode)
                    self._resend_raw(entry[1])
                else:
                    self.send_async(P.SUBMIT_TASKS, entry[1])
        if acked is not None:
            for req_id in acked:
                self._unacked_bulk.pop(req_id, None)

    def _read_loop(self) -> None:
        try:
            while True:
                try:
                    blob = self.conn.recv_bytes()
                except TypeError:
                    # Connection.close() from another thread nulls the fd
                    # mid-recv (os.read(None, ...)) — same benign shutdown
                    # race as EOFError. Only the recv call gets this
                    # treatment; a TypeError in dispatch below is a real bug
                    # and must propagate.
                    raise EOFError("connection closed during recv")
                msg_type, payload = loads_frame(blob)
                if msg_type == "batch":
                    # hub reactor coalesces its per-peer sends (hub._send):
                    # one loads_frame already covered the whole batch.
                    # Hoist the table load out of the inner loop and
                    # memoize the handler across runs of one msg_type
                    # (bulk replies arrive as long same-type runs), and
                    # fold every READY_PUSH in the frame into a single
                    # vector apply — one cache-lock acquisition and one
                    # event set per frame instead of per message.
                    handlers = self._inbound_handlers
                    put = self.task_queue.put
                    ready_ids = None
                    last_mt = None
                    h = None
                    for mt, pl in payload:
                        if mt != last_mt:
                            last_mt = mt
                            h = handlers.get(mt)
                        if mt == P.READY_PUSH:
                            if ready_ids is None:
                                ready_ids = []
                            ready_ids.extend(pl.get("ready", ()))
                        elif h is not None:
                            h(pl)
                        else:
                            put((mt, pl))
                    if ready_ids is not None:
                        self._apply_ready(ready_ids)
                    continue
                self._dispatch_inbound(msg_type, payload)
        except (EOFError, OSError):
            self._fail_pending("hub connection lost")
        except Exception:
            # A dispatch bug used to kill the reader thread bare, which
            # hangs every pending future forever. Surface the bug AND
            # fail the futures loudly, then re-raise so it stays visible
            # as a crash rather than being silently swallowed (GL002).
            log_exc("client reader error")
            self._fail_pending("client reader crashed (see stderr)")
            raise

    def _fail_pending(self, why: str) -> None:
        self._closed = True
        self._ready_evt.set()  # unpark push-waiting wait() loops
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError(why))
        self.task_queue.put((P.KILL, {}))

    def _on_objs_freed(self, oids) -> None:
        """Runs on the reader thread (pubsub callback): drop freed ids
        from the readiness and location caches."""
        with self._obj_cache_lock:
            for oid in oids:
                self._known_ready.pop(oid, None)
                self._resolve_cache.pop(oid, None)
                self._ready_subscribed.discard(oid)
        # drop reader mappings of the freed segments OUTSIDE the cache
        # lock (store has its own; never nest them). Serve payloads map
        # one segment per request — without this the mapping table grows
        # one dead entry per request served.
        for oid in oids:
            self.store.drop_mapping(oid.hex())

    def _on_node_down(self, data) -> None:
        """Runs on the reader thread: a node died — every cached
        location pointing at it is stale, and pooled connections to its
        object agent are dead."""
        node_id = (data or {}).get("node_id")
        if not node_id:
            return
        endpoints = set()
        with self._obj_cache_lock:
            for oid in [
                o for o, info in self._resolve_cache.items()
                if info.get("node_id") == node_id
            ]:
                info = self._resolve_cache.pop(oid)
                if info.get("endpoint"):
                    endpoints.add(info["endpoint"])
        with self._agent_pool_lock:
            for ep in endpoints:
                for conn in self._agent_pool.pop(ep, []):
                    try:
                        conn.close()
                    except Exception:
                        pass

    def _on_ready_push(self, payload) -> None:
        """Runs on the reader thread: the hub pushed a batch of
        newly-ready object ids (readiness subscription, _wait_push)."""
        self._apply_ready(payload.get("ready", ()))

    def _apply_ready(self, ids) -> None:
        """Mark a vector of object ids ready (reader thread). The
        batch-decode path in _read_loop funnels every READY_PUSH of a
        frame through one call, so a bulk submit's completion storm
        costs one lock round trip instead of one per push."""
        with self._obj_cache_lock:
            known = self._known_ready
            subscribed = self._ready_subscribed
            for b in ids:
                known[b] = True
                subscribed.discard(b)
            while len(known) > 65536:
                known.pop(next(iter(known)), None)
        self._ready_evt.set()

    def _dispatch_inbound(self, msg_type, payload):
        # table dispatch, mirroring the hub's {msg_type: bound_method}
        # map (built in __init__); anything unrecognized is a task
        # assignment (worker role) or control message for the executor.
        h = self._inbound_handlers.get(msg_type)
        if h is not None:
            h(payload)
        else:
            self.task_queue.put((msg_type, payload))

    def _on_reply(self, payload):
        req_id = payload["req_id"]
        with self._pending_lock:
            fut = self._pending.pop(req_id, None)
        if fut is not None:
            fut.set_result(payload)

    def _on_pubsub_msg(self, payload):
        cb = self.subscriptions.get(payload["channel"])
        if cb is None:
            return
        # client-published user data rides as an opaque cloudpickle
        # blob (see publish()); hub-internal channels push plain data
        blob = payload.get("blob")
        if blob is not None:
            try:
                data = loads_inline(blob)
            except Exception:
                # a blob this subscriber can't decode (publisher-only
                # module etc.) must not kill the reader thread, but
                # dropping it silently makes the loss undebuggable
                log_exc(
                    f"undecodable pubsub blob on channel "
                    f"{payload.get('channel')!r} (message dropped)"
                )
                return
        else:
            data = payload["data"]
        try:
            cb(data)
        except Exception:
            pass

    def _profile_sink(self, batch: dict) -> None:
        """Sampler flush target (profiling.Sampler, its own daemon
        thread): folded stacks ride the async buffer to the hub. Never
        raises — a half-closed connection must not kill the sampler."""
        if self._closed:
            return
        try:
            self.send_async(P.PROFILE_BATCH, batch)
        except Exception:
            pass

    def _on_stack_dump(self, payload):
        """Reader-thread handler for a brokered `ray_tpu stack` dump.
        Deliberately NOT routed through the task queue: the executor
        being wedged is exactly when a dump is wanted."""
        from . import profiling as _profiling

        try:
            self.send(P.STACK_REPLY, {
                "token": payload.get("token"),
                "pid": os.getpid(),
                "threads": _profiling.dump_threads(),
            })
        except Exception:
            pass

    def stack_dump(self, target: str = "hub", timeout: float = 10.0) -> dict:
        """All-thread stack dump of one runtime process (`ray_tpu
        stack`): target is "hub", a worker id, or a worker pid. The hub
        answers for itself inline and brokers worker targets over their
        control connection (STACK_DUMP/STACK_REPLY)."""
        return self.request(
            P.STACK_REQUEST, {"target": str(target)}, timeout=timeout
        )

    def _on_cancel_task(self, payload):
        # reader-thread fast path: mark before the executor
        # dequeues it AND resolve the caller immediately —
        # the executor may be busy for a long time before it
        # ever sees the queued message (it drops it silently
        # at dequeue; a late duplicate TASK_DONE is ignored
        # because error objects are first-write-wins)
        self.cancelled_tasks.add(payload["task_id"])
        if payload.get("return_ids"):
            blob = dumps_inline(
                exceptions.TaskCancelledError("task was cancelled")
            )
            self.send(
                P.TASK_DONE,
                {
                    "task_id": payload["task_id"],
                    "returns": [
                        (oid, P.VAL_ERROR, blob, 0)
                        for oid in payload["return_ids"]
                    ],
                },
            )

    # Request types safe to retransmit when a reply is slow/lost: reads
    # and idempotent writes. Lost-message tolerance is what the chaos
    # tests (RAY_TPU_CHAOS_DROP) exercise — the reference gets the same
    # property from its retryable gRPC client (rpc/retryable_grpc_client.h).
    _RETRY_SAFE = {
        P.GET, P.WAIT, P.KV_GET, P.KV_PUT, P.KV_KEYS, P.KV_DEL,
        P.GET_ACTOR, P.GET_FUNCTION, P.LIST_STATE, P.CLUSTER_RESOURCES,
        P.PG_READY, P.STREAM_NEXT, P.STREAM_CREDIT, P.FETCH_OBJECT,
        P.REGISTER_JOB,  # idempotent upsert keyed by job_id
        P.RESOLVE_OBJECT,   # pure read of the location directory
        P.SUBSCRIBE_READY,  # idempotent watcher registration
    }
    # Retransmit cadence: capped exponential backoff with full jitter
    # (reference: rpc/retryable_grpc_client.h's exponential backoff —
    # the previous fixed ~2s re-send turned every hub stall into a
    # synchronized retransmit storm from the whole client herd, and is
    # exactly the shape graftlint GL011 now flags). _RETRY_PERIOD_S is
    # the base delay; doubles per resend up to _RETRY_MAX_S.
    _RETRY_PERIOD_S = 2.0
    _RETRY_MAX_S = 30.0

    # adaptive-coalescing bounds (send_async): the window floor keeps
    # per-message overhead amortized at least 16-way under sustained
    # load; the ceiling bounds burst latency and frame size; the byte
    # cap flushes early when large payloads (put_value) stack up
    _COALESCE_FLOOR = 16
    _COALESCE_CEIL = 512
    _COALESCE_MAX_BYTES = 1 << 20
    # auto-batch capacity bound: a window's worth of spliced submits
    # drains early past this many tasks (bounds frame size and the
    # all-or-nothing retransmit unit)
    _AB_MAX = 1024

    # process-wide client generation counter (see self.client_epoch)
    _EPOCH_COUNTER = itertools.count(1)

    def _retry_delay(self, delay: float,
                     cap: Optional[float] = None) -> Tuple[float, float]:
        """(this wait's jittered duration, next backoff step). Full
        jitter on [base/2, base] keeps the mean cadence near base while
        desynchronizing retransmit herds. `cap` bounds the growth
        (default: the retransmit ceiling; _wait_push resyncs cap at 8s
        so a lost push costs seconds, not the full ceiling)."""
        if cap is None:
            cap = self._RETRY_MAX_S
        return delay * (0.5 + 0.5 * random.random()), min(cap, delay * 2.0)

    def request(self, msg_type: str, payload: dict, timeout: Optional[float] = None) -> dict:
        import time as _time
        from concurrent.futures import wait as _fut_wait

        req_id = next(self._req_counter)
        fut: Future = Future()
        with self._pending_lock:
            self._pending[req_id] = fut
        payload = dict(payload, req_id=req_id)
        self.send(msg_type, payload)
        retryable = msg_type in self._RETRY_SAFE and not (
            msg_type == P.KV_PUT and not payload.get("overwrite", True)
        )
        if not retryable or self._RETRY_PERIOD_S <= 0:
            # period <= 0 = retransmit disabled: park on the first send
            # (a zero base must not degenerate into a busy-spin flood)
            return fut.result(timeout=timeout)
        deadline = None if timeout is None else _time.monotonic() + timeout
        delay = self._RETRY_PERIOD_S
        while True:
            remaining, delay = self._retry_delay(delay)
            if deadline is not None:
                remaining = min(remaining, deadline - _time.monotonic())
                if remaining <= 0:
                    raise TimeoutError(f"{msg_type} request timed out")
            # Non-raising wait: chunk expiry must be distinguishable from
            # an EXTERNAL TimeoutError (e.g. a test-harness SIGALRM) —
            # concurrent.futures.TimeoutError IS builtins.TimeoutError, so
            # an except here would swallow cancellation and spin forever.
            _fut_wait([fut], timeout=remaining)
            if fut.done():
                return fut.result()
            if self._closed:
                raise ConnectionError("hub connection lost")
            # reply lost or hub slow: retransmit the same req_id (a
            # duplicate reply finds no pending future and is dropped;
            # the hub's _inflight_reqs dedup keeps one parked waiter —
            # and one traced span — per logical request regardless of
            # how many resends the backoff schedule produces)
            self.send(msg_type, payload)

    # -------------------------------------------------------- runtime tracing
    # All methods below are reached only behind `if self._tracing_live():`
    # — with sampling off and no ambient context (the default) the
    # submit/get/put hot paths pay one attribute load plus one
    # contextvar read each.
    def _tracing_live(self) -> bool:
        return self._trace_on or self._trace_ctx() is not None
    def _trace_begin(self):
        """(trace_id, parent_span_id) for a new sampled operation:
        inherit the ambient context (a user span, or a traced task's
        execute scope in a worker — that's how nested submits stitch),
        else head-sample a fresh trace."""
        from ..util import tracing as _t

        ctx = _t.current_context()
        if ctx is not None:
            return ctx
        r = self._trace_rate
        if r >= 1.0 or random.random() < r:
            return (_t.new_span_id(), None)
        return None

    def _span_rec(self, name: str, stage: str, trace_id: str,
                  span_id: str, parent_id, t0: float, t1: float,
                  **attrs) -> dict:
        """Build one finished runtime span record against the pre-bound
        clock anchor — no per-span import, getpid(), or intermediate
        attrs dict."""
        a = {"stage": stage}
        for k, v in attrs.items():
            a[k] = str(v)
        wall_at = self._wall_at
        return {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "start": wall_at(t0),
            "end": wall_at(t1),
            "pid": self._pid,
            "node_id": self.node_id,
            "attrs": a,
        }

    def _trace_emit(self, name: str, stage: str, trace_id: str,
                    span_id: str, parent_id, t0: float, t1: float,
                    **attrs) -> None:
        """Ship one finished runtime span to the hub (batched onto the
        existing connection; never raises into the traced path)."""
        rec = self._span_rec(name, stage, trace_id, span_id, parent_id,
                             t0, t1, **attrs)
        try:
            self.send_async(P.SPAN_RECORD, rec)
        except Exception:
            pass

    def _traced_send(self, msg_type: str, payload: dict, span_name: str,
                     stage: str, tr: tuple, remember_ids=(),
                     t0: Optional[float] = None, **attrs) -> None:
        """One sampled request: mint the span id, attach the trace
        context to the payload, ship it, emit the client-side span, and
        remember the return ids so a later get() joins the trace.
        `t0` lets the span start before payload encoding (put path)."""
        span_id = self._span_id_hex()
        if t0 is None:
            t0 = time.monotonic()
        payload["trace"] = (tr[0], span_id)
        self.send_async(msg_type, payload)
        self._trace_emit(span_name, stage, tr[0], span_id, tr[1],
                         t0, time.monotonic(), **attrs)
        if remember_ids:
            self._trace_remember(remember_ids, (tr[0], span_id))

    def _trace_remember(self, return_ids, ctx: tuple) -> None:
        # under the cache lock like every other client-side cache: a
        # multi-threaded driver evicting concurrently (or racing a
        # free()) must not KeyError inside the user's submit
        with self._obj_cache_lock:
            refs = self._trace_refs
            for oid in return_ids:
                refs[oid] = ctx
            while len(refs) > 4096:  # FIFO bound; eviction = untraced get
                refs.pop(next(iter(refs)), None)

    def _trace_for_ids(self, oid_list) -> Optional[tuple]:
        """Trace context for a get/fetch: ambient first, else the
        remembered submit context of any requested ref."""
        from ..util import tracing as _t

        ctx = _t.current_context()
        if ctx is not None:
            return ctx
        refs = self._trace_refs
        if not refs:
            return None
        for oid in oid_list:
            ctx = refs.get(oid)
            if ctx is not None:
                return ctx
        return None

    # --------------------------------------------------------------- objects
    def put_value(self, obj: Any, object_id: Optional[ObjectID] = None,
                  force_shm: bool = False, cache: bool = True) -> ObjectID:
        oid = object_id or ObjectID.generate()
        tr = self._trace_begin() if self._tracing_live() else None
        if tr is None:
            kind, payload, size = self.encode_value(oid, obj, force_shm=force_shm)
            self.send_async(
                P.PUT,
                {"object_id": oid.binary(), "kind": kind,
                 "payload": payload, "size": size},
                cost=size if kind == P.VAL_INLINE else 0,
            )
        else:
            t0 = time.monotonic()  # the put span covers the encode too
            kind, payload, size = self.encode_value(oid, obj, force_shm=force_shm)
            self._traced_send(
                P.PUT,
                {"object_id": oid.binary(), "kind": kind,
                 "payload": payload, "size": size},
                "client.put", "put", tr,
                remember_ids=[oid.binary()], t0=t0, size=size,
            )
        if kind == P.VAL_SHM and cache:
            # cache the deserialized original to avoid a re-map on local
            # get. The serve payload codec passes cache=False: the
            # producer never re-reads its own request payload, and 4096
            # cached MiB-scale bodies would pin gigabytes.
            with self._obj_cache_lock:
                self._obj_cache[oid.binary()] = obj
        return oid

    # client-mode puts above this size stream to the hub in chunks and
    # land in the HEAD node's shm store as ordinary VAL_SHM objects
    # (reference: util/client/server/dataservicer.py chunked PutObject);
    # below it they ride inline through the connection as before
    CLIENT_CHUNK_THRESHOLD = 4 * 1024 * 1024
    FETCH_CHUNK = 8 * 1024 * 1024

    def encode_value(self, oid: ObjectID, obj: Any,
                     force_shm: bool = False) -> Tuple[str, Any, int]:
        """Encode a value for transport: inline bytes or shm segment name."""
        from .serialization import RawPayload, dumps_oob

        header, buffers = dumps_oob(obj)
        nbytes = len(header) + sum(b.raw().nbytes for b in buffers)
        # RawPayload (and force_shm=True) is an explicit object-plane
        # request (serve payload codec): never inline it, even below
        # INLINE_THRESHOLD or inside the client-mode CHUNK window — the
        # whole point is one memcpy into shm instead of a pickle ride
        # through the hub
        if not force_shm and not isinstance(obj, RawPayload) and (
            nbytes < INLINE_THRESHOLD
            or (self.inline_only and nbytes < self.CLIENT_CHUNK_THRESHOLD)
        ):
            if buffers:
                blob = dumps_inline((header, [b.raw().tobytes() for b in buffers]))
            else:
                blob = dumps_inline((header, []))
            return P.VAL_INLINE, blob, nbytes
        name = oid.hex()
        if self.inline_only:
            # Stream the segment into the HEAD node's store. Preferred
            # path: out-of-band direct put to the head's object agent —
            # the bytes never enter the hub reactor; the caller's PUT
            # message then flips the object ready. Fallback: PUT_CHUNK
            # relay through the hub (the last chunk makes the object
            # ready cluster-side; the duplicate PUT the caller sends
            # afterwards is a no-op: _object_ready ignores already-ready
            # objects).
            from .object_store import iter_segment_chunks

            raws = [b.raw() for b in buffers]
            fallback = None
            if self._direct_enabled:
                try:
                    self._direct_put(name, *iter_segment_chunks(header, raws))
                    return P.VAL_SHM, name, nbytes
                except Exception as err:
                    fallback = f"{type(err).__name__}: {err}"
            total, chunks = iter_segment_chunks(header, raws)
            sent = 0
            for piece in chunks:
                msg = {
                    "object_id": oid.binary(), "name": name,
                    "offset": sent, "data": piece,
                }
                if fallback is not None and sent == 0:
                    msg["fallback"] = fallback
                sent += len(piece)
                msg["last"] = sent >= total
                self.send(P.PUT_CHUNK, msg)
            return P.VAL_SHM, name, nbytes
        self.store.put_raw(name, header, [b.raw() for b in buffers])
        return P.VAL_SHM, name, nbytes

    def _head_endpoint(self) -> str:
        """The head node's object-agent endpoint for direct puts
        (cached; "" = head serves no agent, stay on the relay)."""
        ep = self._head_agent_endpoint
        if ep is None:
            reply = self.request(P.RESOLVE_OBJECT, {"node_id": "node0"})
            ep = self._head_agent_endpoint = reply.get("endpoint") or ""
        return ep

    def _direct_put(self, name: str, total: int, chunks) -> None:
        """Stream a large client-mode put out-of-band to the head's
        object agent. Raises on ANY irregularity; the caller falls back
        to the PUT_CHUNK hub relay."""
        endpoint = self._head_endpoint()
        if not endpoint:
            raise OSError("head node serves no object agent")
        conn = self._agent_checkout(endpoint)
        ok = False
        try:
            sent = 0
            for piece in chunks:
                sent += len(piece)
                conn.send_bytes(dumps_frame((P.OBJ_PUT, {
                    "name": name, "data": piece, "last": sent >= total,
                })))
            msg_type, p = loads_frame(conn.recv_bytes())
            if msg_type == P.OBJ_ERROR:
                raise OSError(p.get("error") or "agent put failed")
            if msg_type != P.OBJ_PUT_OK:
                raise OSError(f"unexpected frame {msg_type}")
            ok = True
        finally:
            if ok:
                self._agent_checkin(endpoint, conn)
            else:
                try:
                    conn.close()
                except Exception:
                    pass

    def decode_value(self, oid_bytes: bytes, kind: str, payload: Any) -> Any:
        if kind == P.VAL_INLINE:
            header, bufs = loads_inline(payload)
            from .serialization import loads_oob

            return loads_oob(header, bufs)
        if kind == P.VAL_SHM:
            try:
                return self.store.get(payload)
            except FileNotFoundError:
                # segment lives on another node: resolve its location
                # once and pull it DIRECTLY from the owner's object
                # agent (out-of-band object plane), falling back to the
                # hub-relay chunked fetch on any transfer error
                # (reference: object manager pull + ownership
                # directory). Every path streams in chunks so a
                # multi-GB get never materializes twice in one process.
                self._fetch_segment(oid_bytes, payload)
                return self.store.get(payload)
        if kind == P.VAL_ERROR:
            err = loads_inline(payload)
            raise err
        raise ValueError(f"unknown value kind {kind}")

    def _decode_oneshot(self, oid_bytes: bytes, kind: str, payload: Any) -> Any:
        """One-shot consumer decode (serve payload codec). A VAL_SHM
        segment that is NOT already mapped locally is pulled straight
        from the owner's object agent into memory and decoded over the
        pulled bytes (object_store.decode_segment_bytes) — no store
        install, no REPLICA_ADDED registration, no mapping left behind
        for a value read exactly once. Local segments (the same-node
        common case: driver and replicas share one objects dir) take
        the ordinary zero-copy store.get via decode_value, which is
        also the fallback on ANY pull irregularity — its fetch matrix
        ends in the hub relay, so a dead agent degrades, never fails."""
        if kind == P.VAL_SHM and not self.store.contains(payload):
            info = self._resolve_object(oid_bytes) if self._direct_enabled else None
            if (
                info
                and info.get("endpoint")
                and not (
                    info.get("hostname") == self.hostname
                    and info.get("path")
                    and os.path.isfile(info["path"])
                )
            ):
                try:
                    from .object_agent import pull_segment_bytes
                    from .object_store import decode_segment_bytes

                    blob = pull_segment_bytes(info["endpoint"], payload)
                    return decode_segment_bytes(blob)
                except Exception:
                    self._invalidate_resolve(oid_bytes, info.get("endpoint"))
        return self.decode_value(oid_bytes, kind, payload)

    # ------------------------------------------- out-of-band object plane
    def _resolve_object(self, oid_bytes: bytes) -> Optional[dict]:
        """Query (and cache) the hub's ownership/location directory.
        Returns None when the object has no resolvable shm location."""
        with self._obj_cache_lock:
            info = self._resolve_cache.get(oid_bytes)
        if info is not None:
            return info
        reply = self.request(P.RESOLVE_OBJECT, {"object_id": oid_bytes})
        if reply.get("error") or not reply.get("name"):
            return None
        if reply.get("spilled"):
            # relay territory (restore-under-accounting); uncached so a
            # later fetch re-resolves the post-restore location
            return None
        info = {
            "name": reply["name"],
            "node_id": reply.get("node_id"),
            "endpoint": reply.get("endpoint"),
            "hostname": reply.get("hostname"),
            "path": reply.get("path"),
        }
        with self._obj_cache_lock:
            cache = self._resolve_cache
            cache[oid_bytes] = info
            while len(cache) > 4096:  # FIFO bound; eviction = re-resolve
                cache.pop(next(iter(cache)))
        return info

    def _invalidate_resolve(self, oid_bytes: bytes, endpoint: Optional[str]) -> None:
        with self._obj_cache_lock:
            self._resolve_cache.pop(oid_bytes, None)
        if endpoint:
            with self._agent_pool_lock:
                for conn in self._agent_pool.pop(endpoint, []):
                    try:
                        conn.close()
                    except Exception:
                        pass

    def _agent_checkout(self, endpoint: str):
        with self._agent_pool_lock:
            pool = self._agent_pool.get(endpoint)
            if pool:
                return pool.pop()
        return connect_hub(endpoint)

    def _agent_checkin(self, endpoint: str, conn) -> None:
        with self._agent_pool_lock:
            pool = self._agent_pool.setdefault(endpoint, [])
            if len(pool) < 4:
                pool.append(conn)
                return
        try:
            conn.close()
        except Exception:
            pass

    def _direct_pull(self, endpoint: str, name: str, dst_tmp: str) -> None:
        """Stream one segment from a peer's object agent into dst_tmp.
        Raises on ANY irregularity; the caller falls back to the relay."""
        conn = self._agent_checkout(endpoint)
        ok = False
        try:
            conn.send_bytes(dumps_frame((P.OBJ_GET, {"name": name})))
            with open(dst_tmp, "wb") as f:
                while True:
                    msg_type, p = loads_frame(conn.recv_bytes())
                    if msg_type == P.OBJ_ERROR:
                        raise OSError(p.get("error") or "agent fetch failed")
                    if msg_type != P.OBJ_DATA:
                        raise OSError(f"unexpected frame {msg_type}")
                    f.write(p["data"])
                    if p.get("last"):
                        break
            ok = True
        finally:
            if ok:
                self._agent_checkin(endpoint, conn)
            else:
                try:
                    conn.close()
                except Exception:
                    pass

    def _fetch_segment(self, oid_bytes: bytes, name: str) -> None:
        tr = self._trace_for_ids((oid_bytes,)) if self._tracing_live() else None
        if tr is None:
            return self._fetch_segment_impl(oid_bytes, name)
        from ..util.tracing import new_span_id

        span_id = new_span_id()
        t0 = time.monotonic()
        try:
            return self._fetch_segment_impl(oid_bytes, name)
        finally:
            # one span per installed segment: direct object-agent pull,
            # same-host file copy, and the hub-relay fallback all count
            # as the object plane's "transfer" stage
            self._trace_emit(
                "client.fetch_segment", "transfer", tr[0], span_id,
                tr[1], t0, time.monotonic(), object=oid_bytes.hex(),
            )

    def _fetch_segment_impl(self, oid_bytes: bytes, name: str) -> None:
        """Install a remote segment into the local store: same-host
        file copy when the producer's objects dir is visible on this
        machine, direct object-agent stream otherwise, hub relay as the
        fallback of last resort (transfer-path matrix in the README)."""
        fallback_reason = None
        if self._direct_enabled:
            info = self._resolve_object(oid_bytes)
            if info is not None:
                tmp = (
                    self.store._path(name)
                    + f".fetch.{os.getpid()}.{threading.get_ident()}"
                )
                try:
                    src = None
                    if info.get("hostname") == self.hostname:
                        # producer's store is on THIS machine: its
                        # segment file is directly readable
                        cand = info.get("path")
                        if cand and cand != self.store._path(name) \
                                and os.path.isfile(cand):
                            src = cand
                    if src is not None:
                        # same-host shm: the producer's segment is a
                        # local file — copy at memcpy speed, no sockets
                        import shutil

                        shutil.copyfile(src, tmp)
                    elif info.get("endpoint"):
                        self._direct_pull(info["endpoint"], info["name"], tmp)
                    else:
                        raise OSError("no object-agent endpoint")
                    os.replace(tmp, self.store._path(name))
                    if not self.inline_only:
                        # this node's shared store now holds a replica;
                        # the directory can serve later consumers from it
                        # (a client-mode scratch dir is private — not a
                        # replica anyone else could read)
                        self.send_async(P.REPLICA_ADDED, {
                            "object_id": oid_bytes, "node_id": self.node_id,
                        })
                    return
                except Exception as err:  # fall back to the hub relay
                    fallback_reason = f"{type(err).__name__}: {err}"
                    self._invalidate_resolve(oid_bytes, info.get("endpoint"))
                finally:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        self._fetch_segment_chunked(oid_bytes, name, fallback=fallback_reason)

    def _fetch_segment_chunked(self, oid_bytes: bytes, name: str,
                               fallback: Optional[str] = None) -> None:
        """Pull a remote segment into the local store through the hub
        relay in FETCH_CHUNK slices (reference: dataservicer.py chunked
        GetObject). Idempotent offset reads, so the retry-safe request
        path applies per chunk. `fallback` carries the direct-transfer
        failure reason so the hub records the object_transfer_fallback
        event and bumps ray_tpu_object_fallbacks_total."""
        # pid AND thread id: two threads get()ing the same not-yet-local
        # ref fetch independently; same bytes, last replace wins
        tmp = (
            self.store._path(name)
            + f".fetch.{os.getpid()}.{threading.get_ident()}"
        )
        off, total = 0, None
        try:
            with open(tmp, "wb") as f:
                while total is None or off < total:
                    req = {
                        "object_id": oid_bytes,
                        "offset": off,
                        "length": self.FETCH_CHUNK,
                    }
                    if fallback is not None and off == 0:
                        req["fallback"] = fallback
                    reply = self.request(P.FETCH_OBJECT, req)
                    data = reply.get("data")
                    if data is None or (not data and off < (total or 1)):
                        with self._obj_cache_lock:
                            self._known_ready.pop(oid_bytes, None)
                        raise exceptions.ObjectLostError(
                            f"object {oid_bytes.hex()} unavailable: "
                            f"{reply.get('error')}"
                        ) from None
                    f.write(data)
                    off += len(data)
                    total = reply.get("total", off)
            os.replace(tmp, self.store._path(name))
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def get(self, object_ids: Sequence[ObjectID], timeout: Optional[float] = None,
            oneshot: bool = False) -> List[Any]:
        if not self._tracing_live():
            return self._get(object_ids, timeout, oneshot=oneshot)
        ids = [o.binary() for o in object_ids]
        tr = self._trace_for_ids(ids)
        if tr is None:
            return self._get(object_ids, timeout, oneshot=oneshot)
        from ..util.tracing import new_span_id

        span_id = new_span_id()
        t0 = time.monotonic()
        err = None
        try:
            return self._get(object_ids, timeout, trace=(tr[0], span_id),
                             oneshot=oneshot)
        except BaseException as exc:
            err = type(exc).__name__
            raise
        finally:
            attrs = {"n": len(ids)}
            if err is not None:
                attrs["error"] = err
            # the get span ENVELOPS the wait for the result; the
            # analyzer charges only its tail past the last runtime
            # stage to "result_return"
            self._trace_emit(
                "client.get", "result_return", tr[0], span_id, tr[1],
                t0, time.monotonic(), **attrs,
            )
            if err != "GetTimeoutError":
                # terminal get: a LATER re-get of the same (now cached)
                # ref must not re-emit and stretch the finished trace's
                # end-to-end window; a timed-out get keeps its entries
                # so the retry still stitches
                with self._obj_cache_lock:
                    for b in ids:
                        self._trace_refs.pop(b, None)

    def _get(self, object_ids: Sequence[ObjectID],
             timeout: Optional[float] = None,
             trace: Optional[tuple] = None,
             oneshot: bool = False) -> List[Any]:
        out: Dict[bytes, Any] = {}
        missing = []
        with self._obj_cache_lock:
            for oid in object_ids:
                if oid.binary() in self._obj_cache:
                    out[oid.binary()] = self._obj_cache[oid.binary()]
                else:
                    missing.append(oid)
        if missing:
            req = {"object_ids": [o.binary() for o in missing], "timeout": timeout}
            if trace is not None:
                req["trace"] = trace
            reply = self.request(
                P.GET,
                req,
                timeout=None,
            )
            if reply.get("timeout"):
                raise exceptions.GetTimeoutError(
                    f"get() timed out after {timeout}s waiting for {len(missing)} objects"
                )
            errs = []
            for oid_bytes, kind, payload in reply["values"]:
                if kind == P.VAL_ERROR:
                    errs.append(loads_inline(payload))
                    out[oid_bytes] = ("__err__", errs[-1])
                elif oneshot:
                    # one-shot consumer semantics (serve payloads): the
                    # value is read exactly once, so never insert it into
                    # the cache — sustained serving would otherwise pin
                    # thousands of dead MiB-scale bodies there
                    out[oid_bytes] = self._decode_oneshot(oid_bytes, kind, payload)
                else:
                    val = self.decode_value(oid_bytes, kind, payload)
                    out[oid_bytes] = val
                    with self._obj_cache_lock:
                        if len(self._obj_cache) >= 4096:
                            # crude half-eviction keeps the cache bounded
                            for k in list(self._obj_cache)[:2048]:
                                del self._obj_cache[k]
                        self._obj_cache[oid_bytes] = val
            if errs:
                raise errs[0]
        return [out[o.binary()] for o in object_ids]

    def wait(
        self,
        object_ids: Sequence[ObjectID],
        num_returns: int,
        timeout: Optional[float],
        fetch_local: bool = True,
    ) -> Tuple[List[bytes], List[bytes]]:
        ids = [o.binary() for o in object_ids]
        ready_pos, not_ready_pos = self.wait_pos(ids, num_returns, timeout)
        return [ids[i] for i in ready_pos], [ids[i] for i in not_ready_pos]

    def _scan_ready(self, ids: List[bytes], num_returns: int) -> List[int]:
        """Positions of locally-known-ready ids, stopping at
        num_returns hits. Readiness is monotonic except for
        cross-client frees and node-loss reconstruction; in those rare
        races the follow-up get() blocks through reconstruction or
        raises ObjectLostError — the same TOCTOU a hub round-trip reply
        has (decode_value un-caches on loss)."""
        known = self._known_ready
        cache = self._obj_cache
        ready: List[int] = []
        with self._obj_cache_lock:
            for i, b in enumerate(ids):
                if b in known or b in cache:
                    ready.append(i)
                    if len(ready) >= num_returns:
                        break
        return ready

    def wait_pos(
        self,
        ids: List[bytes],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[int], List[int]]:
        """wait() by POSITION in `ids` — the pop-loop shape (1k refs,
        num_returns=1, re-called per pop) stays O(n) per call instead
        of O(n) dict builds on every layer above.

        Fast path: the local readiness cache, fed by READY_PUSH.
        Slow path: ONE readiness subscription for the unknown ids (the
        hub replies with the already-ready subset and pushes the rest
        as producing tasks finish), then park on _ready_evt. The
        periodic re-subscribe below makes lost pushes (chaos drops,
        hub restart races) cost one retry period, not a hang."""
        num_returns = min(num_returns, len(ids))
        if num_returns <= 0:
            return [], list(range(len(ids)))
        ready = self._scan_ready(ids, num_returns)
        if len(ready) < num_returns:
            if not self._ready_push:
                ready = self._wait_request(ids, num_returns, timeout)
            else:
                ready = self._wait_push(ids, num_returns, timeout)
        rset = set(ready)
        return ready, [i for i in range(len(ids)) if i not in rset]

    def _wait_request(self, ids, num_returns, timeout) -> List[int]:
        """Classic parked-WAIT request path (RAY_TPU_READY_PUSH=0)."""
        reply = self.request(
            P.WAIT,
            {"object_ids": ids, "num_returns": num_returns, "timeout": timeout},
        )
        known = self._known_ready
        with self._obj_cache_lock:
            for b in reply["ready"]:
                known[b] = True
            for b in reply.get("also_ready", ()):
                known[b] = True
            while len(known) > 65536:  # FIFO cap; eviction costs a re-ask
                known.pop(next(iter(known)), None)
        rset = set(reply["ready"])
        return [i for i, b in enumerate(ids) if b in rset][:num_returns]

    def _wait_push(self, ids, num_returns, timeout) -> List[int]:
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        # re-subscribe cadence backs off like the request retransmit
        # path (pushes are the primary wake; the periodic resync only
        # covers lost pushes) — capped low so a genuinely lost push
        # costs seconds, not the full retransmit ceiling. The resync
        # must stay alive even with retransmits disabled (period <= 0):
        # a lost push with no re-subscribe is a permanent hang.
        base = self._RETRY_PERIOD_S if self._RETRY_PERIOD_S > 0 else 2.0
        resync = base
        # index-keyed pending set: ready positions accumulate across
        # wakes and each wake re-tests ONLY the still-pending ids. The
        # previous shape rescanned the full ref list on every push wake
        # — O(n) per wake, O(n^2) across a 1k-ref wait whose
        # completions stream in one push at a time.
        pending = dict(enumerate(ids))
        ready: List[int] = []
        known = self._known_ready
        cache = self._obj_cache
        subscribed = self._ready_subscribed
        while True:
            self._ready_evt.clear()
            with self._obj_cache_lock:
                hit: List[int] = []
                for i, b in pending.items():
                    if b in known or b in cache:
                        hit.append(i)
                        if len(ready) + len(hit) >= num_returns:
                            break
                for i in hit:
                    del pending[i]
                    ready.append(i)
                if len(ready) >= num_returns:
                    # positions in ascending order, matching the
                    # single-scan contract wait_pos callers rely on
                    ready.sort()
                    return ready
                # register any pending id not already covered by a live
                # subscription (cross-call memo: a pop-loop subscribes
                # each id ONCE total, not once per dry call); the reply
                # carries the subset that is already ready hub-side
                need = [b for b in pending.values() if b not in subscribed]
            if self._closed:
                raise ConnectionError("hub connection lost")
            if need:
                reply = self.request(
                    P.SUBSCRIBE_READY, {"object_ids": need}
                )
                with self._obj_cache_lock:
                    rdy = reply.get("ready", ())
                    for b in rdy:
                        known[b] = True
                    rdy = set(rdy)
                    subscribed.update(b for b in need if b not in rdy)
                    while len(known) > 65536:
                        known.pop(next(iter(known)), None)
                    # hard bound: ids whose producers never finish would
                    # pin the memo; past the cap, drop it wholesale (the
                    # cost is one redundant re-subscribe per waiter)
                    if len(subscribed) > 131072:
                        subscribed.clear()
                continue  # re-scan with the reply folded in
            remaining, backed_off = self._retry_delay(resync, cap=8.0)
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    ready.sort()
                    return ready
            if not self._ready_evt.wait(remaining):
                # a full resync period with no push: drop the pending
                # ids from the memo so the next pass re-subscribes —
                # the reply re-syncs readiness even if pushes were lost
                # (chaos) — and back the period off (no fixed-interval
                # retransmit)
                resync = backed_off
                with self._obj_cache_lock:
                    subscribed.difference_update(pending.values())
            else:
                # pushes are flowing again: later losses should re-sync
                # at the base cadence, not the backed-off one
                resync = base
                if len(pending) >= 256:
                    # push debounce for BIG waits: completions stream
                    # one push at a time, and on a busy single-core
                    # host every wake of this thread steals the GIL
                    # from the hub thread mid-dispatch (they share this
                    # process for local drivers). One short sleep
                    # batches the next few pushes into a single
                    # wake/scan instead of one wake per completed task;
                    # small waits (and the TAIL of big ones) stay
                    # latency-exact.
                    time.sleep(0.002)

    def free(self, object_ids: Sequence[ObjectID]) -> None:
        with self._obj_cache_lock:
            for o in object_ids:
                self._obj_cache.pop(o.binary(), None)
                self._known_ready.pop(o.binary(), None)
                self._resolve_cache.pop(o.binary(), None)
                self._trace_refs.pop(o.binary(), None)
        for o in object_ids:
            # drop any locally-fetched copy of a remote segment too
            self.store.free(o.hex())
        self.send_async(P.FREE, {"object_ids": [o.binary() for o in object_ids]})

    def release_owned(self, oid: bytes) -> None:
        """Owner dropped its last local handle to a never-shared ref:
        the hub may free the object (ownership GC; reference analogue:
        ReferenceCounter RemoveLocalReference -> eviction).

        Called from ObjectRef.__del__ — must stay lock-free (plain
        append only); the flusher thread ships the batch. __del__ may
        preempt a thread that already holds our locks, so taking one
        here can deadlock — flush()'s swap-then-drain tolerates the
        unlocked append."""
        self._release_buf.append(oid)  # graftlint: disable=GL001

    # ------------------------------------------------------------------ jobs
    def register_job(
        self,
        job_id: str,
        tenant: str = "default",
        priority: int = 0,
        quota: Optional[Dict[str, float]] = None,
    ) -> None:
        """Register this client's scheduling identity with the hub's
        multi-tenant policy engine (fairsched): tenant id, priority,
        optional resource quota. Later submits are stamped with it."""
        self.job_id = job_id
        self.tenant = tenant or "default"
        self.priority = int(priority or 0)
        self.request(P.REGISTER_JOB, {
            "job_id": job_id, "tenant": self.tenant,
            "priority": self.priority,
            # tri-state: None = keep the tenant's existing cap;
            # {} = explicitly lift it; a dict = replace it
            "quota": None if quota is None else dict(quota),
        })

    def _current_job_identity(self) -> tuple:
        """(job_id, tenant, priority) in effect for a submit from this
        thread/context right now — the execution context's identity
        (set per task/actor call in workers) over the client-wide
        registered one. Submit templates key their spliced prefix on
        this tuple so an identity change rebuilds the baked options."""
        ident = _job_identity.get()
        if ident is None:
            ident = (self.job_id, self.tenant, self.priority)
        return ident

    def _stamp_job(self, options: dict) -> None:
        """Attach the job identity to a submit's options (per-call
        priority=/tenant= overrides win via setdefault)."""
        job_id, tenant, priority = self._current_job_identity()
        explicit_tenant = options.get("tenant")
        if explicit_tenant and explicit_tenant != tenant:
            # per-call tenant OVERRIDE: this is deliberately not the
            # registered job's work — attaching its job_id/priority
            # would account another tenant's traffic to this job
            return
        # each field stamps independently: a per-call priority= without
        # any registered job (job_id None) must still follow nested
        # submits, or fanned-out work escapes quota/priority
        if job_id is not None:
            options.setdefault("job_id", job_id)
        if tenant:
            options.setdefault("tenant", tenant)
        if priority:
            options.setdefault("priority", priority)

    # ----------------------------------------------------------------- tasks
    def register_function(self, fn_id: str, blob: bytes) -> None:
        if fn_id not in self._seen_fns:
            # per-process memo of exported fn digests (content-bounded)
            self._seen_fns[fn_id] = True  # graftlint: disable=GL009
            self.send_async(P.REGISTER_FUNCTION, {"fn_id": fn_id, "blob": blob})

    def submit_task(
        self,
        fn_id: str,
        args_kind: str,
        args_payload: Any,
        arg_dep_ids: List[bytes],
        num_returns: int,
        resources: Dict[str, float],
        options: dict,
        return_task_id: bool = False,
    ):
        task_id = TaskID.generate()
        return_ids = [ObjectID.generate() for _ in range(num_returns)]
        self._stamp_job(options)
        payload = {
            "task_id": task_id.binary(),
            "fn_id": fn_id,
            "args_kind": args_kind,
            "args_payload": args_payload,
            "arg_deps": arg_dep_ids,
            "return_ids": [r.binary() for r in return_ids],
            "resources": resources,
            "options": options,
        }
        tr = self._trace_begin() if self._tracing_live() else None
        if tr is None:
            self.send_async(P.SUBMIT_TASK, payload)
        else:
            self._traced_send(
                P.SUBMIT_TASK, payload, "client.submit", "submit", tr,
                remember_ids=payload["return_ids"], fn_id=fn_id,
            )
        if return_task_id:
            return task_id.binary(), return_ids
        return return_ids

    def submit_many(
        self,
        fn_id: str,
        encoded: List[tuple],
        num_returns: int,
        resources: Dict[str, float],
        options: dict,
    ) -> Tuple[List[bytes], List[List[bytes]]]:
        """Ship N homogeneous tasks in ONE P.SUBMIT_TASKS wire frame
        (RemoteFunction.map). ``encoded`` is [(args_kind, args_payload,
        arg_dep_ids), ...]; fn_id/resources/options are shared by every
        task and travel once in the outer payload. All task and return
        ids are drawn in one slab from the entropy pool. Returns
        (task_ids, return_ids_per_task) as raw bytes.

        Delivery: the hub acks the batch via REPLY(req_id); an unacked
        batch is retransmitted by the flusher (_scan_unacked) and
        deduplicated per task on the hub, so a chaos-dropped frame
        loses nothing. With retransmit disabled (period <= 0) the send
        is fire-and-forget like submit_task."""
        n = len(encoded)
        self._stamp_job(options)
        slab = id_slab(n * (1 + num_returns))
        task_ids = slab[:n]
        rid_rows = [
            slab[n + i * num_returns: n + (i + 1) * num_returns]
            for i in range(n)
        ]
        payload = {
            "fn_id": fn_id,
            "resources": resources,
            "options": options,
            "tasks": [
                {
                    "task_id": task_ids[i],
                    "args_kind": e[0],
                    "args_payload": e[1],
                    "arg_deps": e[2],
                    "return_ids": rid_rows[i],
                }
                for i, e in enumerate(encoded)
            ],
        }
        if self._RETRY_PERIOD_S > 0:
            req_id = next(self._req_counter)
            payload["req_id"] = req_id
            fut: Future = Future()
            with self._pending_lock:
                self._pending[req_id] = fut
            wait_s, nxt = self._retry_delay(self._RETRY_PERIOD_S)
            while len(self._unacked_bulk) >= 256:
                # FIFO bound: an evicted entry just loses retransmit
                # coverage; its ack (if it comes) still resolves the
                # pending future and is dropped there
                self._unacked_bulk.pop(
                    next(iter(self._unacked_bulk)), None)
            self._unacked_bulk[req_id] = [
                fut, payload, time.monotonic() + wait_s, nxt,
            ]
        tr = self._trace_begin() if self._tracing_live() else None
        if tr is None:
            self.send_async(P.SUBMIT_TASKS, payload)
        else:
            # ONE client.submit span for the whole batch; the hub fans
            # it out to N hub.admit children (_on_submit_tasks)
            self._traced_send(
                P.SUBMIT_TASKS, payload, "client.submit", "submit", tr,
                remember_ids=[r for row in rid_rows for r in row],
                fn_id=fn_id, n=n,
            )
        return task_ids, rid_rows

    def create_actor(
        self,
        fn_id: str,
        args_kind: str,
        args_payload: Any,
        arg_dep_ids: List[bytes],
        resources: Dict[str, float],
        options: dict,
    ) -> Tuple[ActorID, ObjectID]:
        actor_id = ActorID.generate()
        ready_id = ObjectID.generate()
        self._stamp_job(options)
        payload = {
            "actor_id": actor_id.binary(),
            "fn_id": fn_id,
            "args_kind": args_kind,
            "args_payload": args_payload,
            "arg_deps": arg_dep_ids,
            "ready_id": ready_id.binary(),
            "resources": resources,
            "options": options,
        }
        if options.get("name"):
            # Named creation is synchronous so duplicate names raise here,
            # matching the reference (actor.py _remote name check via GCS).
            reply = self.request(P.CREATE_ACTOR, payload)
            if reply.get("error"):
                raise ValueError(reply["error"])
        else:
            self.send(P.CREATE_ACTOR, payload)
        return actor_id, ready_id

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args_kind: str,
        args_payload: Any,
        arg_dep_ids: List[bytes],
        num_returns: int,
        options: dict,
        return_task_id: bool = False,
    ):
        task_id = TaskID.generate()
        return_ids = [ObjectID.generate() for _ in range(num_returns)]
        # actor calls carry no resources (no quota charge), but the
        # identity must ride along so submits NESTED inside the method
        # inherit it (worker_process._adopt_job_identity)
        self._stamp_job(options)
        payload = {
            "task_id": task_id.binary(),
            "actor_id": actor_id.binary(),
            "method": method_name,
            "args_kind": args_kind,
            "args_payload": args_payload,
            "arg_deps": arg_dep_ids,
            "return_ids": [r.binary() for r in return_ids],
            "options": options,
        }
        tr = self._trace_begin() if self._tracing_live() else None
        if tr is None:
            self.send_async(P.SUBMIT_ACTOR_TASK, payload)
        else:
            self._traced_send(
                P.SUBMIT_ACTOR_TASK, payload, "client.submit_actor",
                "submit", tr, remember_ids=payload["return_ids"],
                method=method_name,
            )
        if return_task_id:
            return task_id.binary(), return_ids
        return return_ids

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.send(P.KILL_ACTOR, {"actor_id": actor_id.binary(), "no_restart": no_restart})

    def cancel(self, object_id: ObjectID, force: bool = False) -> None:
        self.send(P.CANCEL, {"object_id": object_id.binary(), "force": force})

    # -------------------------------------------------------------- metadata
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        return self.request(P.KV_PUT, {"key": key, "value": value, "overwrite": overwrite})["ok"]

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self.request(P.KV_GET, {"key": key})["value"]

    def kv_del(self, key: bytes) -> bool:
        return self.request(P.KV_DEL, {"key": key})["ok"]

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        return self.request(P.KV_KEYS, {"prefix": prefix})["keys"]

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        reply = self.request(P.GET_ACTOR, {"name": name, "namespace": namespace})
        return reply.get("actor_id")

    def create_placement_group(
        self,
        bundles,
        strategy: str,
        name: str = "",
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> bytes:
        payload = {"bundles": bundles, "strategy": strategy, "name": name}
        # explicit overrides land BEFORE stamping: _stamp_job must see
        # a tenant override to know not to attach this job's identity
        if tenant is not None:
            payload["tenant"] = tenant
        if priority is not None:
            payload["priority"] = int(priority)
        self._stamp_job(payload)
        reply = self.request(P.CREATE_PG, payload)
        if reply.get("error"):
            raise ValueError(reply["error"])
        return reply["pg_id"]

    def remove_placement_group(self, pg_id: bytes) -> None:
        self.send(P.REMOVE_PG, {"pg_id": pg_id})

    def pg_ready(self, pg_id: bytes, timeout: Optional[float] = None) -> bool:
        reply = self.request(P.PG_READY, {"pg_id": pg_id, "timeout": timeout})
        return reply["ready"]

    def list_state(self, kind: str, **params) -> list:
        # extra params pass through to the hub's _on_list_state (e.g.
        # trace_id narrows kind="traces" to one trace's spans)
        return self.request(P.LIST_STATE, dict(params, kind=kind))["items"]

    def cluster_resources(self, available: bool = False) -> dict:
        return self.request(P.CLUSTER_RESOURCES, {"available": available})["resources"]

    def subscribe(self, channel: str, callback) -> None:
        """Push-based pubsub (reference: GCS pubsub channels)."""
        self.subscriptions[channel] = callback
        self.send(P.SUBSCRIBE, {"channel": channel})

    def publish(self, channel: str, data) -> None:
        # pre-serialize user data with cloudpickle so the plain-pickle
        # frame codec never meets a raw __main__-level object; the hub
        # forwards the blob opaque and the subscriber unwraps it
        # (_on_pubsub_msg)
        self.send_async(P.PUBLISH, {"channel": channel, "blob": dumps_inline(data)})

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._ready_evt.set()  # unpark any push-waiting wait()
            with self._agent_pool_lock:
                pools, self._agent_pool = self._agent_pool, {}
            for conns in pools.values():
                for c in conns:
                    try:
                        c.close()
                    except Exception:
                        pass
            try:
                # Half-close the stream BEFORE closing the fd: the reader
                # thread is blocked in os.read() and that in-flight read
                # keeps the open file description alive past conn.close(),
                # so no FIN ever reaches the hub — which then keeps this
                # connection (and every registry keyed on it: fairsched
                # jobs, subscriptions, ready-watches) until process exit.
                # shutdown() on a dup'd handle tears the stream down under
                # the blocked read: the reader sees EOF immediately and
                # the hub's reactor (or owning shard) gets its disconnect.
                import socket as _socket

                fd = os.dup(self.conn.fileno())
                try:
                    s = _socket.socket(fileno=fd)
                except OSError:
                    os.close(fd)
                else:
                    try:
                        s.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    s.close()
            except Exception:
                pass
            try:
                self.conn.close()
            except Exception:
                pass
