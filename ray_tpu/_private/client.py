"""Core client: the per-process endpoint talking to the control hub.

This is the analogue of the reference's CoreWorker (reference:
src/ray/core_worker/core_worker.h:166) — one instance per driver or
worker process. It owns:
  - the hub connection + a reader thread that demultiplexes inbound
    messages (task assignments vs request replies),
  - the local view of the shm object store,
  - an inline-object cache (objects are immutable, so caching is safe).

Both the driver and workers use this same class; workers additionally
run an executor loop (worker_process.py) fed from `task_queue`.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from concurrent.futures import Future
from multiprocessing.connection import Client as MpClient
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import exceptions
from . import protocol as P
from .debug import log_exc
from .ids import ActorID, ObjectID, TaskID
from .object_store import INLINE_THRESHOLD, ShmObjectStore
from .serialization import (
    dumps_frame,
    dumps_inline,
    loads_frame,
    loads_inline,
)


# Per-CALL job identity override for worker processes: an actor with
# max_concurrency > 1 serves callers from different tenants at once, so
# identity must live in the execution context (one per pool thread /
# asyncio task), never in shared CoreClient fields — or caller A's
# nested submits get stamped with caller B's tenant and quota.
# worker_process._adopt_job_identity sets it; _stamp_job reads it first.
from contextvars import ContextVar

_job_identity: ContextVar = ContextVar("ray_tpu_job_identity", default=None)


def connect_hub(addr: str):
    """Dial the hub: "tcp://host:port" (cluster mode) or an AF_UNIX path."""
    if addr.startswith("tcp://"):
        host, port = addr[6:].rsplit(":", 1)
        return MpClient((host, int(port)), family="AF_INET")
    return MpClient(addr, family="AF_UNIX")


class CoreClient:
    def __init__(self, hub_addr: str, session_dir: str, role: str, worker_id: str):
        self.role = role
        self.worker_id = worker_id
        self.session_dir = session_dir
        self.node_id = os.environ.get("RAY_TPU_NODE_ID", "node0")
        self.store = ShmObjectStore(session_dir)
        self.conn = connect_hub(hub_addr)
        self._send_lock = threading.Lock()
        self._send_buf: List[tuple] = []
        self._buf_evt = threading.Event()
        # ownership-GC release ids, appended from ObjectRef.__del__.
        # __del__ can run at ANY allocation point — including while THIS
        # thread already holds _send_lock (GC during dumps_inline) — so
        # the only safe operation there is a plain list.append (GIL-
        # atomic, lock-free). The flusher thread drains it.
        self._release_buf: List[bytes] = []
        self._req_counter = itertools.count()
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._obj_cache: Dict[bytes, Any] = {}
        self._obj_cache_lock = threading.Lock()
        # object ids known ready (from wait replies); insertion-ordered
        # for FIFO bounding. Cleared per-id by free().
        self._known_ready: Dict[bytes, bool] = {}
        self._seen_fns: Dict[str, Any] = {}
        self.task_queue: "queue.Queue" = queue.Queue()
        self.cancelled_tasks: set = set()  # task_ids to drop at dequeue
        # client mode (ray_tpu.init(address=...)): no shared shm with
        # the cluster — small puts travel inline through the hub
        # connection, large ones chunk-stream into the head-node store
        # (encode_value / _fetch_segment_chunked)
        self.inline_only = False
        # multi-tenant scheduling identity (set by register_job): every
        # submit/PG-create from this client is stamped with it so the
        # hub's fairsched engine can order/quota/preempt per tenant
        self.job_id: Optional[str] = None
        self.tenant: Optional[str] = None
        self.priority: int = 0
        # pubsub: channel -> callback(data); callbacks run on the reader
        # thread, so keep them light (print/enqueue)
        self.subscriptions: Dict[str, Any] = {}
        self._closed = False
        # inbound dispatch table (the hub-side _handlers symmetric):
        # resolved once here instead of a per-message if/elif chain on
        # the reader thread
        self._inbound_handlers = {
            P.REPLY: self._on_reply,
            P.PUBSUB_MSG: self._on_pubsub_msg,
            P.CANCEL_TASK: self._on_cancel_task,
        }
        self.send(P.HELLO, {"role": role, "worker_id": worker_id,
                            "pid": os.getpid(), "node_id": self.node_id})
        # shm frees anywhere in the cluster invalidate the local wait()
        # readiness cache (otherwise a freed object reports ready here
        # indefinitely; the follow-up get would raise ObjectLostError)
        self.subscriptions["__obj_freed__"] = self._on_objs_freed
        self.send(P.SUBSCRIBE, {"channel": "__obj_freed__"})
        self._reader = threading.Thread(target=self._read_loop, daemon=True, name="core-client-reader")
        self._reader.start()

        self._flusher = threading.Thread(target=self._flush_loop, daemon=True, name="core-client-flusher")
        self._flusher.start()

    # ------------------------------------------------------------------ wire
    #
    # Two send paths: `send` (immediate, flushes any buffered messages first
    # so total order is preserved) and `send_async` (buffered). Buffering
    # coalesces submit storms into one syscall + one hub wakeup per batch —
    # this matters because the hub thread shares the driver's GIL; without
    # batching every message pays a GIL handoff (~sys.getswitchinterval()).
    def send(self, msg_type: str, payload: dict) -> None:
        with self._send_lock:
            if self._send_buf:
                buf, self._send_buf = self._send_buf, []
                buf.append((msg_type, payload))
                self.conn.send_bytes(dumps_frame(("batch", buf)))
            else:
                self.conn.send_bytes(dumps_frame((msg_type, payload)))

    def send_async(self, msg_type: str, payload: dict) -> None:
        with self._send_lock:
            self._send_buf.append((msg_type, payload))
            n = len(self._send_buf)
            if n >= 128:
                buf, self._send_buf = self._send_buf, []
                self.conn.send_bytes(dumps_frame(("batch", buf)))
                return
        if n == 1:
            self._buf_evt.set()

    def flush(self) -> None:
        with self._send_lock:
            if self._release_buf:
                # swap-then-drain: concurrent __del__ appends land either
                # in the drained list (sent now) or the fresh one (next
                # flush) — nothing is lost, no lock needed on their side
                drained = self._release_buf
                self._release_buf = []
                self._send_buf.append(
                    ("release_owned", {"object_ids": drained})
                )
            if self._send_buf:
                buf, self._send_buf = self._send_buf, []
                self.conn.send_bytes(dumps_frame(("batch", buf)))

    def _flush_loop(self) -> None:
        # Catches stray buffered messages ~0.5ms after the burst ends.
        # The 50ms wait timeout doubles as the drain cadence for the
        # lock-free release buffer (__del__ can't signal the event:
        # Event.set takes a lock, and __del__ may preempt a thread that
        # already holds it).
        while not self._closed:
            self._buf_evt.wait(timeout=0.05)
            self._buf_evt.clear()
            time.sleep(0.0005)
            try:
                self.flush()
            except (OSError, BrokenPipeError):
                return

    def _read_loop(self) -> None:
        try:
            while True:
                try:
                    blob = self.conn.recv_bytes()
                except TypeError:
                    # Connection.close() from another thread nulls the fd
                    # mid-recv (os.read(None, ...)) — same benign shutdown
                    # race as EOFError. Only the recv call gets this
                    # treatment; a TypeError in dispatch below is a real bug
                    # and must propagate.
                    raise EOFError("connection closed during recv")
                msg_type, payload = loads_frame(blob)
                if msg_type == "batch":
                    # hub reactor coalesces its per-peer sends (hub._send)
                    for mt, pl in payload:
                        self._dispatch_inbound(mt, pl)
                    continue
                self._dispatch_inbound(msg_type, payload)
        except (EOFError, OSError):
            self._fail_pending("hub connection lost")
        except Exception:
            # A dispatch bug used to kill the reader thread bare, which
            # hangs every pending future forever. Surface the bug AND
            # fail the futures loudly, then re-raise so it stays visible
            # as a crash rather than being silently swallowed (GL002).
            log_exc("client reader error")
            self._fail_pending("client reader crashed (see stderr)")
            raise

    def _fail_pending(self, why: str) -> None:
        self._closed = True
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError(why))
        self.task_queue.put((P.KILL, {}))

    def _on_objs_freed(self, oids) -> None:
        """Runs on the reader thread (pubsub callback): drop freed ids
        from the readiness cache."""
        with self._obj_cache_lock:
            for oid in oids:
                self._known_ready.pop(oid, None)

    def _dispatch_inbound(self, msg_type, payload):
        # table dispatch, mirroring the hub's {msg_type: bound_method}
        # map (built in __init__); anything unrecognized is a task
        # assignment (worker role) or control message for the executor.
        h = self._inbound_handlers.get(msg_type)
        if h is not None:
            h(payload)
        else:
            self.task_queue.put((msg_type, payload))

    def _on_reply(self, payload):
        req_id = payload["req_id"]
        with self._pending_lock:
            fut = self._pending.pop(req_id, None)
        if fut is not None:
            fut.set_result(payload)

    def _on_pubsub_msg(self, payload):
        cb = self.subscriptions.get(payload["channel"])
        if cb is None:
            return
        # client-published user data rides as an opaque cloudpickle
        # blob (see publish()); hub-internal channels push plain data
        blob = payload.get("blob")
        if blob is not None:
            try:
                data = loads_inline(blob)
            except Exception:
                # a blob this subscriber can't decode (publisher-only
                # module etc.) must not kill the reader thread, but
                # dropping it silently makes the loss undebuggable
                log_exc(
                    f"undecodable pubsub blob on channel "
                    f"{payload.get('channel')!r} (message dropped)"
                )
                return
        else:
            data = payload["data"]
        try:
            cb(data)
        except Exception:
            pass

    def _on_cancel_task(self, payload):
        # reader-thread fast path: mark before the executor
        # dequeues it AND resolve the caller immediately —
        # the executor may be busy for a long time before it
        # ever sees the queued message (it drops it silently
        # at dequeue; a late duplicate TASK_DONE is ignored
        # because error objects are first-write-wins)
        self.cancelled_tasks.add(payload["task_id"])
        if payload.get("return_ids"):
            blob = dumps_inline(
                exceptions.TaskCancelledError("task was cancelled")
            )
            self.send(
                P.TASK_DONE,
                {
                    "task_id": payload["task_id"],
                    "returns": [
                        (oid, P.VAL_ERROR, blob, 0)
                        for oid in payload["return_ids"]
                    ],
                },
            )

    # Request types safe to retransmit when a reply is slow/lost: reads
    # and idempotent writes. Lost-message tolerance is what the chaos
    # tests (RAY_TPU_CHAOS_DROP) exercise — the reference gets the same
    # property from its retryable gRPC client (rpc/retryable_grpc_client.h).
    _RETRY_SAFE = {
        P.GET, P.WAIT, P.KV_GET, P.KV_PUT, P.KV_KEYS, P.KV_DEL,
        P.GET_ACTOR, P.GET_FUNCTION, P.LIST_STATE, P.CLUSTER_RESOURCES,
        P.PG_READY, P.STREAM_NEXT, P.STREAM_CREDIT, P.FETCH_OBJECT,
        P.REGISTER_JOB,  # idempotent upsert keyed by job_id
    }
    _RETRY_PERIOD_S = 2.0

    def request(self, msg_type: str, payload: dict, timeout: Optional[float] = None) -> dict:
        import time as _time
        from concurrent.futures import wait as _fut_wait

        req_id = next(self._req_counter)
        fut: Future = Future()
        with self._pending_lock:
            self._pending[req_id] = fut
        payload = dict(payload, req_id=req_id)
        self.send(msg_type, payload)
        retryable = msg_type in self._RETRY_SAFE and not (
            msg_type == P.KV_PUT and not payload.get("overwrite", True)
        )
        if not retryable:
            return fut.result(timeout=timeout)
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = self._RETRY_PERIOD_S
            if deadline is not None:
                remaining = min(remaining, deadline - _time.monotonic())
                if remaining <= 0:
                    raise TimeoutError(f"{msg_type} request timed out")
            # Non-raising wait: chunk expiry must be distinguishable from
            # an EXTERNAL TimeoutError (e.g. a test-harness SIGALRM) —
            # concurrent.futures.TimeoutError IS builtins.TimeoutError, so
            # an except here would swallow cancellation and spin forever.
            _fut_wait([fut], timeout=remaining)
            if fut.done():
                return fut.result()
            if self._closed:
                raise ConnectionError("hub connection lost")
            # reply lost or hub slow: retransmit the same req_id (a
            # duplicate reply finds no pending future and is dropped)
            self.send(msg_type, payload)

    # --------------------------------------------------------------- objects
    def put_value(self, obj: Any, object_id: Optional[ObjectID] = None) -> ObjectID:
        oid = object_id or ObjectID.generate()
        kind, payload, size = self.encode_value(oid, obj)
        self.send_async(P.PUT, {"object_id": oid.binary(), "kind": kind, "payload": payload, "size": size})
        if kind == P.VAL_SHM:
            # cache the deserialized original to avoid a re-map on local get
            with self._obj_cache_lock:
                self._obj_cache[oid.binary()] = obj
        return oid

    # client-mode puts above this size stream to the hub in chunks and
    # land in the HEAD node's shm store as ordinary VAL_SHM objects
    # (reference: util/client/server/dataservicer.py chunked PutObject);
    # below it they ride inline through the connection as before
    CLIENT_CHUNK_THRESHOLD = 4 * 1024 * 1024
    FETCH_CHUNK = 8 * 1024 * 1024

    def encode_value(self, oid: ObjectID, obj: Any) -> Tuple[str, Any, int]:
        """Encode a value for transport: inline bytes or shm segment name."""
        from .serialization import dumps_oob

        header, buffers = dumps_oob(obj)
        nbytes = len(header) + sum(b.raw().nbytes for b in buffers)
        if nbytes < INLINE_THRESHOLD or (
            self.inline_only and nbytes < self.CLIENT_CHUNK_THRESHOLD
        ):
            if buffers:
                blob = dumps_inline((header, [b.raw().tobytes() for b in buffers]))
            else:
                blob = dumps_inline((header, []))
            return P.VAL_INLINE, blob, nbytes
        name = oid.hex()
        if self.inline_only:
            # chunk-stream the segment bytes to the hub; the last chunk
            # makes the object ready cluster-side (the duplicate PUT the
            # caller sends afterwards is a no-op: _object_ready ignores
            # already-ready objects)
            from .object_store import iter_segment_chunks

            total, chunks = iter_segment_chunks(
                header, [b.raw() for b in buffers]
            )
            sent = 0
            for piece in chunks:
                sent += len(piece)
                self.send(P.PUT_CHUNK, {
                    "object_id": oid.binary(), "name": name,
                    "data": piece, "last": sent >= total,
                })
            return P.VAL_SHM, name, nbytes
        self.store.put_raw(name, header, [b.raw() for b in buffers])
        return P.VAL_SHM, name, nbytes

    def decode_value(self, oid_bytes: bytes, kind: str, payload: Any) -> Any:
        if kind == P.VAL_INLINE:
            header, bufs = loads_inline(payload)
            from .serialization import loads_oob

            return loads_oob(header, bufs)
        if kind == P.VAL_SHM:
            try:
                return self.store.get(payload)
            except FileNotFoundError:
                # segment lives on another node: pull it through the hub
                # (reference: object manager pull, ownership directory).
                # Shm-less clients stream it in chunks so a multi-GB get
                # never materializes twice in hub memory.
                if self.inline_only:
                    self._fetch_segment_chunked(oid_bytes, payload)
                else:
                    reply = self.request(
                        P.FETCH_OBJECT, {"object_id": oid_bytes}
                    )
                    if reply.get("data") is None:
                        with self._obj_cache_lock:
                            self._known_ready.pop(oid_bytes, None)
                        raise exceptions.ObjectLostError(
                            f"object {oid_bytes.hex()} unavailable: "
                            f"{reply.get('error')}"
                        ) from None
                    self.store.write_segment(payload, reply["data"])
                return self.store.get(payload)
        if kind == P.VAL_ERROR:
            err = loads_inline(payload)
            raise err
        raise ValueError(f"unknown value kind {kind}")

    def _fetch_segment_chunked(self, oid_bytes: bytes, name: str) -> None:
        """Pull a remote segment into the local scratch store in
        FETCH_CHUNK slices (reference: dataservicer.py chunked
        GetObject). Idempotent offset reads, so the retry-safe request
        path applies per chunk."""
        # pid AND thread id: two threads get()ing the same not-yet-local
        # ref fetch independently; same bytes, last replace wins
        tmp = (
            self.store._path(name)
            + f".fetch.{os.getpid()}.{threading.get_ident()}"
        )
        off, total = 0, None
        try:
            with open(tmp, "wb") as f:
                while total is None or off < total:
                    reply = self.request(P.FETCH_OBJECT, {
                        "object_id": oid_bytes,
                        "offset": off,
                        "length": self.FETCH_CHUNK,
                    })
                    data = reply.get("data")
                    if data is None or (not data and off < (total or 1)):
                        with self._obj_cache_lock:
                            self._known_ready.pop(oid_bytes, None)
                        raise exceptions.ObjectLostError(
                            f"object {oid_bytes.hex()} unavailable: "
                            f"{reply.get('error')}"
                        ) from None
                    f.write(data)
                    off += len(data)
                    total = reply.get("total", off)
            os.replace(tmp, self.store._path(name))
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def get(self, object_ids: Sequence[ObjectID], timeout: Optional[float] = None) -> List[Any]:
        out: Dict[bytes, Any] = {}
        missing = []
        with self._obj_cache_lock:
            for oid in object_ids:
                if oid.binary() in self._obj_cache:
                    out[oid.binary()] = self._obj_cache[oid.binary()]
                else:
                    missing.append(oid)
        if missing:
            reply = self.request(
                P.GET,
                {"object_ids": [o.binary() for o in missing], "timeout": timeout},
                timeout=None,
            )
            if reply.get("timeout"):
                raise exceptions.GetTimeoutError(
                    f"get() timed out after {timeout}s waiting for {len(missing)} objects"
                )
            errs = []
            for oid_bytes, kind, payload in reply["values"]:
                if kind == P.VAL_ERROR:
                    errs.append(loads_inline(payload))
                    out[oid_bytes] = ("__err__", errs[-1])
                else:
                    val = self.decode_value(oid_bytes, kind, payload)
                    out[oid_bytes] = val
                    with self._obj_cache_lock:
                        if len(self._obj_cache) >= 4096:
                            # crude half-eviction keeps the cache bounded
                            for k in list(self._obj_cache)[:2048]:
                                del self._obj_cache[k]
                        self._obj_cache[oid_bytes] = val
            if errs:
                raise errs[0]
        return [out[o.binary()] for o in object_ids]

    def wait(
        self,
        object_ids: Sequence[ObjectID],
        num_returns: int,
        timeout: Optional[float],
        fetch_local: bool = True,
    ) -> Tuple[List[bytes], List[bytes]]:
        ids = [o.binary() for o in object_ids]
        # Local fast path: readiness already known from a prior wait
        # reply (also_ready) or a cached value — a wait() pop-loop over
        # 1k refs then costs a handful of round trips instead of one per
        # ref. Readiness is monotonic except for cross-client frees and
        # node-loss reconstruction; in those rare races the follow-up
        # get() blocks through reconstruction or raises ObjectLostError
        # — the same TOCTOU a hub round-trip reply has (decode_value
        # un-caches on loss, below).
        known = self._known_ready
        with self._obj_cache_lock:
            ready_local = [
                b for b in ids if b in known or b in self._obj_cache
            ]
        if len(ready_local) >= num_returns:
            ready = ready_local[:num_returns]
            rset = set(ready)
            return ready, [b for b in ids if b not in rset]
        reply = self.request(
            P.WAIT,
            {"object_ids": ids, "num_returns": num_returns, "timeout": timeout},
        )
        with self._obj_cache_lock:
            for b in reply["ready"]:
                known[b] = True
            for b in reply.get("also_ready", ()):
                known[b] = True
            while len(known) > 65536:  # FIFO cap; eviction costs a re-ask
                known.pop(next(iter(known)), None)
        return reply["ready"], reply["not_ready"]

    def free(self, object_ids: Sequence[ObjectID]) -> None:
        with self._obj_cache_lock:
            for o in object_ids:
                self._obj_cache.pop(o.binary(), None)
                self._known_ready.pop(o.binary(), None)
        for o in object_ids:
            # drop any locally-fetched copy of a remote segment too
            self.store.free(o.hex())
        self.send_async(P.FREE, {"object_ids": [o.binary() for o in object_ids]})

    def release_owned(self, oid: bytes) -> None:
        """Owner dropped its last local handle to a never-shared ref:
        the hub may free the object (ownership GC; reference analogue:
        ReferenceCounter RemoveLocalReference -> eviction).

        Called from ObjectRef.__del__ — must stay lock-free (plain
        append only); the flusher thread ships the batch. __del__ may
        preempt a thread that already holds our locks, so taking one
        here can deadlock — flush()'s swap-then-drain tolerates the
        unlocked append."""
        self._release_buf.append(oid)  # graftlint: disable=GL001

    # ------------------------------------------------------------------ jobs
    def register_job(
        self,
        job_id: str,
        tenant: str = "default",
        priority: int = 0,
        quota: Optional[Dict[str, float]] = None,
    ) -> None:
        """Register this client's scheduling identity with the hub's
        multi-tenant policy engine (fairsched): tenant id, priority,
        optional resource quota. Later submits are stamped with it."""
        self.job_id = job_id
        self.tenant = tenant or "default"
        self.priority = int(priority or 0)
        self.request(P.REGISTER_JOB, {
            "job_id": job_id, "tenant": self.tenant,
            "priority": self.priority,
            # tri-state: None = keep the tenant's existing cap;
            # {} = explicitly lift it; a dict = replace it
            "quota": None if quota is None else dict(quota),
        })

    def _stamp_job(self, options: dict) -> None:
        """Attach the job identity to a submit's options (per-call
        priority=/tenant= overrides win via setdefault). The execution
        context's identity (set per task/actor call in workers) takes
        precedence over the client-wide registered one."""
        ident = _job_identity.get()
        if ident is None:
            ident = (self.job_id, self.tenant, self.priority)
        job_id, tenant, priority = ident
        explicit_tenant = options.get("tenant")
        if explicit_tenant and explicit_tenant != tenant:
            # per-call tenant OVERRIDE: this is deliberately not the
            # registered job's work — attaching its job_id/priority
            # would account another tenant's traffic to this job
            return
        # each field stamps independently: a per-call priority= without
        # any registered job (job_id None) must still follow nested
        # submits, or fanned-out work escapes quota/priority
        if job_id is not None:
            options.setdefault("job_id", job_id)
        if tenant:
            options.setdefault("tenant", tenant)
        if priority:
            options.setdefault("priority", priority)

    # ----------------------------------------------------------------- tasks
    def register_function(self, fn_id: str, blob: bytes) -> None:
        if fn_id not in self._seen_fns:
            # per-process memo of exported fn digests (content-bounded)
            self._seen_fns[fn_id] = True  # graftlint: disable=GL009
            self.send_async(P.REGISTER_FUNCTION, {"fn_id": fn_id, "blob": blob})

    def submit_task(
        self,
        fn_id: str,
        args_kind: str,
        args_payload: Any,
        arg_dep_ids: List[bytes],
        num_returns: int,
        resources: Dict[str, float],
        options: dict,
        return_task_id: bool = False,
    ):
        task_id = TaskID.generate()
        return_ids = [ObjectID.generate() for _ in range(num_returns)]
        self._stamp_job(options)
        self.send_async(
            P.SUBMIT_TASK,
            {
                "task_id": task_id.binary(),
                "fn_id": fn_id,
                "args_kind": args_kind,
                "args_payload": args_payload,
                "arg_deps": arg_dep_ids,
                "return_ids": [r.binary() for r in return_ids],
                "resources": resources,
                "options": options,
            },
        )
        if return_task_id:
            return task_id.binary(), return_ids
        return return_ids

    def create_actor(
        self,
        fn_id: str,
        args_kind: str,
        args_payload: Any,
        arg_dep_ids: List[bytes],
        resources: Dict[str, float],
        options: dict,
    ) -> Tuple[ActorID, ObjectID]:
        actor_id = ActorID.generate()
        ready_id = ObjectID.generate()
        self._stamp_job(options)
        payload = {
            "actor_id": actor_id.binary(),
            "fn_id": fn_id,
            "args_kind": args_kind,
            "args_payload": args_payload,
            "arg_deps": arg_dep_ids,
            "ready_id": ready_id.binary(),
            "resources": resources,
            "options": options,
        }
        if options.get("name"):
            # Named creation is synchronous so duplicate names raise here,
            # matching the reference (actor.py _remote name check via GCS).
            reply = self.request(P.CREATE_ACTOR, payload)
            if reply.get("error"):
                raise ValueError(reply["error"])
        else:
            self.send(P.CREATE_ACTOR, payload)
        return actor_id, ready_id

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args_kind: str,
        args_payload: Any,
        arg_dep_ids: List[bytes],
        num_returns: int,
        options: dict,
        return_task_id: bool = False,
    ):
        task_id = TaskID.generate()
        return_ids = [ObjectID.generate() for _ in range(num_returns)]
        # actor calls carry no resources (no quota charge), but the
        # identity must ride along so submits NESTED inside the method
        # inherit it (worker_process._adopt_job_identity)
        self._stamp_job(options)
        self.send_async(
            P.SUBMIT_ACTOR_TASK,
            {
                "task_id": task_id.binary(),
                "actor_id": actor_id.binary(),
                "method": method_name,
                "args_kind": args_kind,
                "args_payload": args_payload,
                "arg_deps": arg_dep_ids,
                "return_ids": [r.binary() for r in return_ids],
                "options": options,
            },
        )
        if return_task_id:
            return task_id.binary(), return_ids
        return return_ids

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.send(P.KILL_ACTOR, {"actor_id": actor_id.binary(), "no_restart": no_restart})

    def cancel(self, object_id: ObjectID, force: bool = False) -> None:
        self.send(P.CANCEL, {"object_id": object_id.binary(), "force": force})

    # -------------------------------------------------------------- metadata
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        return self.request(P.KV_PUT, {"key": key, "value": value, "overwrite": overwrite})["ok"]

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self.request(P.KV_GET, {"key": key})["value"]

    def kv_del(self, key: bytes) -> bool:
        return self.request(P.KV_DEL, {"key": key})["ok"]

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        return self.request(P.KV_KEYS, {"prefix": prefix})["keys"]

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        reply = self.request(P.GET_ACTOR, {"name": name, "namespace": namespace})
        return reply.get("actor_id")

    def create_placement_group(
        self,
        bundles,
        strategy: str,
        name: str = "",
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> bytes:
        payload = {"bundles": bundles, "strategy": strategy, "name": name}
        # explicit overrides land BEFORE stamping: _stamp_job must see
        # a tenant override to know not to attach this job's identity
        if tenant is not None:
            payload["tenant"] = tenant
        if priority is not None:
            payload["priority"] = int(priority)
        self._stamp_job(payload)
        reply = self.request(P.CREATE_PG, payload)
        if reply.get("error"):
            raise ValueError(reply["error"])
        return reply["pg_id"]

    def remove_placement_group(self, pg_id: bytes) -> None:
        self.send(P.REMOVE_PG, {"pg_id": pg_id})

    def pg_ready(self, pg_id: bytes, timeout: Optional[float] = None) -> bool:
        reply = self.request(P.PG_READY, {"pg_id": pg_id, "timeout": timeout})
        return reply["ready"]

    def list_state(self, kind: str) -> list:
        return self.request(P.LIST_STATE, {"kind": kind})["items"]

    def cluster_resources(self, available: bool = False) -> dict:
        return self.request(P.CLUSTER_RESOURCES, {"available": available})["resources"]

    def subscribe(self, channel: str, callback) -> None:
        """Push-based pubsub (reference: GCS pubsub channels)."""
        self.subscriptions[channel] = callback
        self.send(P.SUBSCRIBE, {"channel": channel})

    def publish(self, channel: str, data) -> None:
        # pre-serialize user data with cloudpickle so the plain-pickle
        # frame codec never meets a raw __main__-level object; the hub
        # forwards the blob opaque and the subscriber unwraps it
        # (_on_pubsub_msg)
        self.send_async(P.PUBLISH, {"channel": channel, "blob": dumps_inline(data)})

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self.conn.close()
            except Exception:
                pass
