"""Multi-reactor control plane: per-client reactor shards.

With ``RAY_TPU_HUB_SHARDS`` > 1 the hub stops being one epoll reactor in
one thread and becomes N **reactor shards** plus one **state plane**:

    client conns ──┐
                   ├── shard 0 (thread: selector + wire codec + outbox)──┐
    client conns ──┘                                                     │
    client conns ──── shard 1 ───────────────────────────────────────────┤
        ...                                                              │ SPSC rings
    client conns ──── shard N-1 ─────────────────────────────────────────┤
                                                                         ▼
                          state plane (thread: scheduler+fairsched service,
                                       object-directory service, timers,
                                       flight recorder, metrics registry)

Each accepted connection is owned by exactly one shard: that shard's
selector polls it, that shard decodes its inbound frames (the PR 2 wire
codec fast path runs there), and that shard — and only that shard —
writes its outbound frames.  The scheduler (+ fairsched) and the object
directory live behind the state plane as single-thread-owned *state
services*: shards reach them exclusively through an in-process message
ring (``ShardRing``) — never by touching hub attributes (graftlint
GL010 polices exactly that).  Replies flow back the same way: the state
plane batches per-peer messages (the PR 2 outbox shape) and hands each
batch to the owning shard's outbound ring for encode + send.

Why one ordered ring per shard rather than one ring per (shard,
service): the wire protocol relies on per-connection FIFO (HELLO before
the first PUT decides ``_conn_node``; REGISTER_FUNCTION must precede a
SUBMIT_TASK naming the fn; STREAM_YIELD must precede STREAM_END).  A
connection's messages split across two independently-drained queues can
reorder across the service boundary, so the shard's dispatch table
*tags* each message with its owning service and the single ring
preserves arrival order end-to-end; the services themselves stay
single-consumer (SPSC holds: one shard producer, one state-plane
consumer per ring).

``RAY_TPU_HUB_SHARDS=1`` (the default resolves to
``min(4, os.cpu_count())``, i.e. 1 on single-core hosts) keeps the
original single-reactor ``Hub._run`` loop — byte-for-byte the same wire
behavior, zero new threads.

Reference: this is the GCS/raylet split (gcs_server.h owning global
state, per-node raylets owning client traffic, reached by RPC) re-done
natively inside one process, per the PAPER.md L3/L4 layer map.
"""

from __future__ import annotations

import os
import selectors
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .debug import log_exc
from .serialization import dumps_frame, loads_frame

# ---------------------------------------------------------------- routing
# msg_type -> owning state service.  The scheduler service owns task and
# actor placement, fairsched (jobs/tenants/quota/preemption), placement
# groups, nodes/workers, and introspection; the object-directory service
# owns the object/ownership tables, streams, kv, and pubsub fan-out.
# Shards build their per-connection dispatch tables from this map; an
# unknown message type defaults to the scheduler service (matching the
# monolithic hub, where unknown types are dropped by the handler table).
SCHEDULER_MSGS = frozenset({
    "hello", "submit_task", "submit_tasks", "task_done", "create_actor",
    "actor_ready",
    "submit_actor_task", "kill_actor", "cancel", "create_pg", "remove_pg",
    "pg_ready", "get_actor", "register_job", "register_node",
    "worker_exited", "node_heartbeat", "register_function", "get_function",
    "cluster_resources", "list_state", "shutdown", "span_record",
    "metric_record", "profile_batch", "stack_request", "stack_reply",
})
OBJECT_MSGS = frozenset({
    "put", "get", "wait", "free", "release_owned", "resolve_object",
    "replica_added", "subscribe_ready", "fetch_object", "obj_read_reply",
    "put_chunk", "stream_yield", "stream_end", "stream_next",
    "stream_credit", "kv_put", "kv_get", "kv_del", "kv_keys",
    "subscribe", "publish", "log_record",
})
SERVICE_OF: Dict[str, str] = {mt: "scheduler" for mt in SCHEDULER_MSGS}
SERVICE_OF.update({mt: "objects" for mt in OBJECT_MSGS})

# internal ring sentinels (never valid wire msg_types)
CONN_LOST = "__conn_lost__"
SHARD_EVENT = "__shard_event__"


def resolve_shard_count(config_value: int = 0) -> int:
    """0 = auto: min(4, cpu count). Clamped to >= 1."""
    n = int(config_value or 0)
    if n <= 0:
        n = min(4, os.cpu_count() or 1)
    return max(1, n)


class ShardRing:
    """SPSC message ring: ONE producer thread appends, ONE consumer
    thread drains.  deque append/popleft are GIL-atomic, so the ring
    itself needs no lock; ``wake`` signals the consumer (an Event.set
    for the state plane, a self-pipe write for a shard)."""

    __slots__ = ("_q", "_wake")

    def __init__(self, wake):
        self._q = deque()
        self._wake = wake

    def push(self, item) -> None:
        self._q.append(item)
        self._wake()

    def drain(self) -> list:
        q = self._q
        out = []
        while q:
            try:
                out.append(q.popleft())
            except IndexError:  # pragma: no cover - single consumer
                break
        return out

    def __len__(self) -> int:
        return len(self._q)


class StateService:
    """One single-thread-owned slice of hub state (scheduler+fairsched,
    or the object directory).  Everything it owns is mutated only on
    the state-plane thread; shards deliver work through the ring and
    this dispatch seam — the only supported way in (GL010)."""

    __slots__ = ("name", "_dispatch", "processed")

    def __init__(self, name: str, dispatch):
        self.name = name
        self._dispatch = dispatch  # bound hub handler (state-plane only)
        self.processed = 0

    def handle(self, conn, msg_type: str, payload) -> None:
        self.processed += 1
        self._dispatch(conn, msg_type, payload)


class ShardStats:
    """Per-shard reactor counters, written ONLY by the shard thread.
    The state plane reads them at scrape time (_merge_shard_metrics) —
    plain int/float loads, safe under the GIL — and renders them as
    builtin series with a ``shard`` label."""

    __slots__ = (
        "wakeups", "drain_saturated", "frames_sent", "flush_buckets",
        "flush_sum", "flush_count", "conns", "accepted", "backpressure",
    )

    # messages coalesced per outbound frame — THE shared constant (the
    # hub's _FLUSH_BOUNDS aliases this) so single-reactor and per-shard
    # flush histograms always carry identical boundaries
    FLUSH_BOUNDS = (1.0, 4.0, 16.0, 64.0, 128.0, 512.0)

    def __init__(self):
        self.wakeups = 0
        self.drain_saturated = 0
        self.frames_sent = 0
        self.flush_buckets = [0] * len(self.FLUSH_BOUNDS)
        self.flush_sum = 0.0
        self.flush_count = 0
        self.conns = 0
        self.accepted = 0
        self.backpressure = 0

    def observe_flush(self, n_msgs: int) -> None:
        self.frames_sent += 1
        self.flush_sum += n_msgs
        self.flush_count += 1
        for i, b in enumerate(self.FLUSH_BOUNDS):
            if n_msgs <= b:
                self.flush_buckets[i] += 1
                break


class ReactorShard(threading.Thread):
    """One reactor thread owning a subset of the hub's connections.

    Owns: its selector, its wake pipe, the sockets assigned to it, the
    wire codec for those sockets (decode inbound, encode outbound), and
    its per-connection dispatch table (msg_type -> state-service tag).

    Does NOT own — and must never touch (GL010) — any scheduler/object
    /fairsched/registry state: every decoded message is pushed onto
    ``state_ring`` and every reply arrives pre-batched on ``outbound``.

    Shard 0 additionally owns the accept socket and deals new
    connections round-robin to all shards via their ``adopt`` API.
    """

    def __init__(self, idx: int, state_ring: ShardRing, drain_budget: int,
                 listener=None, trace_on: bool = False):
        super().__init__(daemon=True, name=f"ray-tpu-hub-shard-{idx}")
        self.idx = idx
        self.stats = ShardStats()
        self._state_ring = state_ring
        self._drain_budget = drain_budget
        # runtime tracing live in this session? If so, stamp traced
        # inbound messages with the decode time so the state plane can
        # attribute ring-wait latency (it emits the span — this thread
        # only annotates the payload it already owns, GL010-clean).
        # False (sampling off) keeps the drain loop byte-identical.
        self._trace_on = trace_on
        self._listener = listener  # shard 0 only
        self._accept_seq = 0
        self.peers: List["ReactorShard"] = []  # set by the hub before start
        # control ring: ("adopt", conn) from the accepting shard
        self._inbox = ShardRing(self._wake)
        # outbound ring: (conn, [(msg_type, payload), ...]) batches from
        # the state plane; this shard encodes one frame per batch
        self.outbound = ShardRing(self._wake)
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_w, False)
        self._stopping = False
        # per-connection dispatch tables, built from the shared service
        # map; attached per-conn at adopt time so a future per-conn
        # override (e.g. a read-only client) costs nothing extra here
        self._routes: Dict[str, str] = dict(SERVICE_OF)
        self._conn_routes: Dict[Any, Dict[str, str]] = {}
        self._sel: Optional[selectors.BaseSelector] = None

    # ------------------------------------------------------------- control
    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"\0")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wake is already pending; or shutting down

    def adopt(self, conn) -> None:
        """Hand a connection to this shard (called by the accepting
        shard, or by the hub for bookkeeping-free test injection)."""
        self._inbox.push(("adopt", conn))

    def expel(self, conn) -> None:
        """State plane -> this shard: forcibly drop one owned conn
        (chaos conn_kill / heartbeat-miss eviction). The unregister must
        happen on THIS thread (it owns the selector); cleanup flows back
        as CONN_LOST exactly like an organic EOF, and the state plane
        closes the socket after its registry sweep."""
        self._inbox.push(("expel", conn))

    def post(self, conn, msgs: list) -> None:
        """State plane -> this shard: one per-peer batch to encode+send."""
        self.outbound.push((conn, msgs))

    def stop(self) -> None:
        self._stopping = True
        self._wake()

    # -------------------------------------------------------------- reactor
    def run(self) -> None:  # pragma: no cover - exercised via Hub tests
        try:
            self._run_reactor()
        except Exception:
            log_exc(f"hub shard {self.idx} FATAL error")
            self._state_ring.push(
                (None, None, SHARD_EVENT,
                 {"kind": "shard_fatal", "shard": self.idx})
            )
            # a dead shard must not strand its peers: report every owned
            # connection lost so the state plane cleans their registries
            # and closes the sockets (clients see EOF instead of hanging
            # on a reactor that will never poll them again), and stops
            # posting replies into this shard's never-drained ring
            for conn in list(self._conn_routes):
                self._conn_routes.pop(conn, None)
                self._state_ring.push((conn, None, CONN_LOST, None))
        finally:
            sel = self._sel
            if sel is not None:
                try:
                    sel.close()
                except Exception:
                    pass
            # wake-pipe fds are NOT closed here: the state plane may
            # still call post()->_wake(), and writing into a recycled
            # fd number would corrupt whatever stream reused it. The
            # hub closes them via close_wakeups() after joining us.

    def close_wakeups(self) -> None:
        """Release the wake pipe. Only safe once no thread can call
        post()/adopt()/stop() on this shard again (hub teardown, after
        join) — a write into a recycled fd number is stream corruption."""
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass

    def _run_reactor(self) -> None:
        sel = self._sel = selectors.DefaultSelector()
        sel.register(self._wake_r, selectors.EVENT_READ, "__wake__")
        if self._listener is not None:
            lsock = self._listener._listener._socket
            sel.register(lsock, selectors.EVENT_READ, "__accept__")
        while True:
            events = sel.select(None)
            self.stats.wakeups += 1
            # backpressure check ONCE per wake: while the state plane is
            # behind the high-water mark, skip reading sockets (the fds
            # stay level-triggered readable; kernel buffers throttle the
            # peers) but keep accepting, adopting, and — crucially —
            # flushing outbound, since delivering replies is what lets
            # clients progress and the backlog drain.
            throttled = len(self._state_ring) > self.RING_HIGH_WATER
            if throttled:
                self.stats.backpressure += 1
            for key, _mask in events:
                tag = key.data
                if tag == "__wake__":
                    try:
                        os.read(self._wake_r, 65536)
                    except OSError:
                        pass
                elif tag == "__accept__":
                    self._accept()
                elif not throttled:
                    self._drain_conn(tag)
            self._drain_inbox(sel)
            self._flush_outbound()
            if self._stopping:
                self._flush_outbound()  # anything posted since the wake
                return
            if throttled:
                time.sleep(0.001)  # one nap per wake, replies already out

    def _accept(self) -> None:
        try:
            conn = self._listener.accept()
        except Exception:
            log_exc(f"hub shard {self.idx} accept error")
            return
        target = self.peers[self._accept_seq % len(self.peers)]
        self._accept_seq += 1
        self.stats.accepted += 1
        if target is self:
            self._register(self._sel, conn)
        else:
            target.adopt(conn)

    def _drain_inbox(self, sel) -> None:
        for op, conn in self._inbox.drain():
            if op == "adopt":
                self._register(sel, conn)
            elif op == "expel" and conn in self._conn_routes:
                self._drop_conn(conn)

    def _register(self, sel, conn) -> None:
        try:
            sel.register(conn, selectors.EVENT_READ, conn)
        except Exception:
            log_exc(f"hub shard {self.idx} register error")
            return
        self._conn_routes[conn] = self._routes
        self.stats.conns += 1

    def _drop_conn(self, conn) -> None:
        """EOF/error: leave the selector, tell the state plane.  The
        state plane closes the socket after its cleanup so the fd can't
        be reused by a racing accept while service state still maps it."""
        sel = self._sel
        if sel is not None:
            try:
                sel.unregister(conn)
            except (KeyError, ValueError, OSError):
                pass
        if self._conn_routes.pop(conn, None) is not None:
            self.stats.conns -= 1
        self._state_ring.push((conn, None, CONN_LOST, None))

    # state-ring high-water mark: the monolithic reactor bounded
    # in-flight work by handling inline (kernel socket buffers were the
    # queue); N decoding shards feeding one state plane need an explicit
    # bound or a submit storm grows the ring without limit (GL005's bug
    # class). Enforced once per reactor wake in _run_reactor.
    RING_HIGH_WATER = 8192

    def _drain_conn(self, conn) -> None:
        """Drain one peer's burst — the same bounded-fairness shape as
        the monolithic reactor — but every decoded message is routed to
        its state service's queue instead of being handled here."""
        routes = self._conn_routes.get(conn)
        if routes is None:
            routes = self._routes
        push = self._state_ring.push
        budget = self._drain_budget
        try:
            while True:
                blob = conn.recv_bytes()
                msg_type, payload = loads_frame(blob)
                if self._trace_on:
                    self._stamp_trace(msg_type, payload)
                # the dispatch table tags the message with its owning
                # state service; "batch" frames stay intact (tag None —
                # the state plane routes the inner messages, and the
                # chaos-drop hook checks the OUTER type, exactly as in
                # the single-reactor path)
                push((conn, routes.get(msg_type), msg_type, payload))
                budget -= len(payload) if msg_type == "batch" else 1
                if budget <= 0:
                    if conn.poll(0):
                        self.stats.drain_saturated += 1
                    break
                if not conn.poll(0):
                    break
        except (EOFError, OSError):
            self._drop_conn(conn)
        except Exception:
            log_exc(f"hub shard {self.idx} reactor error (dropping conn)")
            self._drop_conn(conn)

    @staticmethod
    def _stamp_trace(msg_type: str, payload) -> None:
        """Annotate traced messages with this shard's decode time so
        the state plane can emit the ring-wait span (hub._ring_wait_span
        pops the stamp). Runs only with tracing live; touches nothing
        but the payload this shard just decoded."""
        now = time.monotonic()
        if msg_type == "batch":
            for _mt, pl in payload:
                if type(pl) is dict and "trace" in pl:
                    pl["_ring_t"] = now
        elif type(payload) is dict and "trace" in payload:
            payload["_ring_t"] = now

    def _flush_outbound(self) -> None:
        for conn, msgs in self.outbound.drain():
            self.stats.observe_flush(len(msgs))
            try:
                if len(msgs) == 1:
                    conn.send_bytes(dumps_frame(msgs[0]))
                else:
                    conn.send_bytes(dumps_frame(("batch", msgs)))
            except (OSError, BrokenPipeError, EOFError):
                pass  # peer is going away; its read side will EOF soon
            except Exception:
                # an unpicklable reply must cost that one frame, never
                # the shard thread (which owns every other peer here)
                log_exc(f"hub shard {self.idx} outbound encode error")
