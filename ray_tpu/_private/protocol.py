"""Wire protocol between clients (driver/workers) and the control hub.

The reference splits control flow across gRPC services (GCS, raylet,
worker-to-worker; reference: src/ray/protobuf/*.proto, 21 files). On a
TPU host the control plane is node-local, so we use framed pickle over
AF_UNIX sockets (multiprocessing.connection) — one hub, star topology.
Bulk data never rides these messages; it goes through the shm object
store (object_store.py).

The hub end of every connection may be a single reactor or one of N
reactor shards (RAY_TPU_HUB_SHARDS, hub_shards.py); the protocol is
identical either way — sharding is invisible on the wire. The only
per-connection guarantee clients rely on is FIFO delivery of their own
messages, which each owning shard preserves end-to-end.

Every message is a (msg_type:str, payload:dict) pair encoded with
serialization.dumps_frame. Frames carry a one-byte codec marker:
``b"P"`` (stdlib pickle — the fast path; control frames are dicts of
primitives/bytes) or ``b"C"`` (cloudpickle — payload blobs, and the
automatic fallback for any frame stdlib pickle rejects). Both decode
via serialization.loads_frame. Several messages may be coalesced into
one ("batch", [(msg_type, payload), ...]) frame by either side
(client send_async buffering; hub outbox flush).
"""

# client -> hub
HELLO = "hello"
SUBMIT_TASK = "submit_task"
SUBMIT_TASKS = "submit_tasks"  # N homogeneous tasks in ONE frame
                               # (RemoteFunction.map / submit_many /
                               # the client's transparent auto-batch):
                               # {fn_id, resources, options, tasks:
                               # [{task_id, args_kind, args_payload,
                               # arg_deps, return_ids}, ...], req_id}.
                               # The shared fields are hoisted out of
                               # the per-task dicts; the hub acks via
                               # REPLY(req_id) so the client can
                               # retransmit a dropped batch (per-task
                               # dedup on task_id makes replay safe).
                               # Optional "pipeline": False (spliced by
                               # auto-batched frames) keeps the batch
                               # out of bulk worker pipelining — plain
                               # .remote() placement semantics; absent
                               # = True for the explicit bulk paths.
                               # Auto-batched frames are SPLICED from a
                               # cached opcode prefix plus hand-emitted
                               # per-task fragments (serialization.py)
                               # — indistinguishable on the wire from a
                               # dumps_frame encoding of the same dict
PUT = "put"
GET = "get"
WAIT = "wait"
FREE = "free"
RELEASE_OWNED = "release_owned"  # owner-side GC: the last local handle
                                 # died with the ref never pickled, so
                                 # no other holder can exist — free the
                                 # object(s). Batched client-side (rides
                                 # the next flush's "batch" frame)
CREATE_ACTOR = "create_actor"
SUBMIT_ACTOR_TASK = "submit_actor_task"
KILL_ACTOR = "kill_actor"
CANCEL = "cancel"
REGISTER_FUNCTION = "register_function"
GET_FUNCTION = "get_function"
KV_PUT = "kv_put"
KV_GET = "kv_get"
KV_DEL = "kv_del"
KV_KEYS = "kv_keys"
CREATE_PG = "create_pg"
REMOVE_PG = "remove_pg"
PG_READY = "pg_ready"
GET_ACTOR = "get_actor"
LIST_STATE = "list_state"
CLUSTER_RESOURCES = "cluster_resources"
SHUTDOWN = "shutdown"
REGISTER_JOB = "register_job"  # driver/job -> hub: scheduling identity
                               # {job_id, tenant, priority, quota} for
                               # the fairsched policy engine (multi-
                               # tenant priority/fair-share/preemption)

# worker -> hub
TASK_DONE = "task_done"
ACTOR_READY = "actor_ready"

# any process -> hub: one finished tracing span (util/tracing.py — user
# spans and the runtime's own stage spans share this message; the hub
# indexes them per trace_id for list_state("traces")). Distributed
# trace CONTEXT does not get its own message: a sampled request carries
# an optional "trace": (trace_id, parent_span_id) field inside the
# SUBMIT_TASK / SUBMIT_ACTOR_TASK / GET / PUT payload, and the hub
# forwards (trace_id, its-dispatch-span-id) in EXEC_* payloads so
# worker-side spans and nested submits stitch into the same trace.
# Absent the field (sampling off, the default) every path is untouched.
SPAN_RECORD = "span_record"

# any process -> hub: one util.metrics recording (counter inc / gauge
# set / histogram observe); the hub folds it into its metric registry
METRIC_RECORD = "metric_record"

# any process -> hub: one flush of the sampling profiler's locally
# folded stacks (profiling.py — opt-in via RAY_TPU_PROFILE_HZ, default
# off: with the sampler never started this message type never appears
# on the wire). Payload: {pid, kind ("driver"/"worker"/"hub"/...),
# samples: {collapsed-stack-key: count}, overhead, hz} — the hub folds
# the deltas into its bounded profile store (list_state("profile"))
# and exports the per-process overhead ratio as a builtin gauge.
PROFILE_BATCH = "profile_batch"

# on-demand all-thread stack dumps (`ray_tpu stack`, reference: `ray
# stack` / py-spy dump). No profiler needed — the dump reads
# sys._current_frames() at request time.
STACK_REQUEST = "stack_request"  # client -> hub: {target, req_id} where
                                 # target is "hub", a worker id, or a
                                 # pid; hub-target answered inline,
                                 # otherwise forwarded as STACK_DUMP
STACK_DUMP = "stack_dump"        # hub -> worker/client: {token} — dump
                                 # your threads and reply STACK_REPLY
STACK_REPLY = "stack_reply"      # process -> hub: {token, threads:
                                 # [{thread, daemon, frames}, ...]} —
                                 # routed back to the parked requester

# streaming generators (reference: _raylet.pyx:280 ObjectRefGenerator)
STREAM_YIELD = "stream_yield"    # worker -> hub: one yielded value
STREAM_END = "stream_end"        # worker -> hub: generator exhausted/raised
STREAM_NEXT = "stream_next"      # client -> hub: resolve the i-th ref
STREAM_CREDIT = "stream_credit"  # worker -> hub: backpressure wait

# node agent <-> hub (multi-host: one agent per host, reference analogue
# src/ray/raylet/node_manager.h:122 registering with the GCS)
REGISTER_NODE = "register_node"
NODE_HEARTBEAT = "node_heartbeat"  # agent -> hub: cpu/rss/worker gauges
SPAWN_WORKER = "spawn_worker"      # hub -> agent: fork a worker process
WORKER_EXITED = "worker_exited"    # agent -> hub: child died pre-connect
KILL_WORKER = "kill_worker"        # hub -> agent: SIGKILL a worker (task
                                   # timeout / hung-worker watchdog — a
                                   # stalled process ignores the
                                   # cooperative KILL message)
OBJ_READ = "obj_read"              # hub -> agent: read a shm segment
OBJ_READ_REPLY = "obj_read_reply"  # agent -> hub: segment bytes
OBJ_UNLINK = "obj_unlink"          # hub -> agent: free a shm segment
OBJ_SPILL = "obj_spill"            # hub -> agent: move a segment to disk
OBJ_RESTORE = "obj_restore"        # hub -> agent: move it back to shm
FETCH_OBJECT = "fetch_object"      # client -> hub: pull a remote segment
                                   # (optional offset/length for chunked
                                   # streaming to shm-less clients). The
                                   # hub-RELAY path: the out-of-band
                                   # object plane (RESOLVE_OBJECT +
                                   # object_agent.py) is tried first and
                                   # falls back here; a "fallback" field
                                   # on the first chunk records the
                                   # object_transfer_fallback event
PUT_CHUNK = "put_chunk"            # client -> hub: one slice of a large
                                   # put streamed over the connection
                                   # (reference: util/client/server/
                                   # dataservicer.py chunked PutObject).
                                   # Carries an explicit "offset" so a
                                   # replayed chunk (retransmit after a
                                   # lost reply) rewrites the same bytes
                                   # instead of corrupting the segment

# ---- out-of-band object plane (reference: the ownership directory +
# PullManager/object-manager direct transfer split, src/ray/
# object_manager/ + core_worker/reference_count.h ownership): bulk
# object bytes move peer<->peer over per-node object_agent endpoints
# (object_agent.py), NOT through the hub reactor; the hub only answers
# location queries and tracks the replica set.
RESOLVE_OBJECT = "resolve_object"  # client -> hub: where does this shm
                                   # object live? -> {name, size, node_id,
                                   # endpoint, path, spilled}. Clients
                                   # cache the answer; the cache is
                                   # invalidated by the __obj_freed__ and
                                   # __node_down__ pubsub channels
REPLICA_ADDED = "replica_added"    # client -> hub (async): a direct fetch
                                   # installed a copy of the segment on
                                   # this node; the directory adds it to
                                   # the object's replica set

# client <-> object agent, on the agent's own endpoint (never the hub
# conn). Same dumps_frame framing; request/response, replies read
# inline by the caller rather than through a dispatch table.
OBJ_GET = "obj_get"        # client -> agent: stream me a segment
OBJ_DATA = "obj_data"      # agent -> client: one 8 MiB chunk {data,
                           # total, last}
OBJ_PUT = "obj_put"        # client -> agent: one inbound chunk {name,
                           # data, last}
OBJ_PUT_OK = "obj_put_ok"  # agent -> client: whole put landed {size}
OBJ_ERROR = "obj_error"    # agent -> client: fetch/put failed {error};
                           # the caller falls back to the hub relay

# ---- readiness push (reference: the core worker's object-ready
# callbacks from the local memory store instead of polling GCS): a
# wait() over not-ready refs subscribes ONCE; the hub pushes ready sets
# as producing tasks finish, so a 1k-ref pop-loop costs one
# subscription plus pushes instead of a round trip per poll.
SUBSCRIBE_READY = "subscribe_ready"  # client -> hub: {object_ids} ->
                                     # reply {ready: [...]} for the
                                     # already-ready subset; the rest are
                                     # registered for push
READY_PUSH = "ready_push"            # hub -> client: {ready: [oids]}

# hub -> worker
EXEC_TASK = "exec_task"
EXEC_ACTOR_CREATE = "exec_actor_create"
EXEC_ACTOR_TASK = "exec_actor_task"
KILL = "kill"
CANCEL_TASK = "cancel_task"  # hub -> worker: drop a queued task

# pubsub (reference: src/ray/pubsub/ long-poll publisher; here
# subscribers hold persistent conns so publish is a direct push)
SUBSCRIBE = "subscribe"      # client -> hub: {channel}
PUBLISH = "publish"          # client -> hub -> subscribers: {channel, blob}
                             # blob = dumps_inline(user data) — opaque to
                             # the hub, unwrapped by the subscriber; only
                             # hub-INTERNAL publishes use a plain {channel,
                             # data} body (primitives only — raw user
                             # objects must never ride a frame unblobbed)
PUBSUB_MSG = "pubsub_msg"    # hub -> subscriber push
LOG_RECORD = "log_record"    # worker -> hub: stdout/stderr line batch

# hub -> client
REPLY = "reply"

# object value kinds (in GET replies and TASK_DONE returns)
VAL_INLINE = "inline"  # payload = serialized bytes
VAL_SHM = "shm"  # payload = segment name
VAL_ERROR = "error"  # payload = serialized exception
