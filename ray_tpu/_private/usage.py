"""Opt-out usage-stats collection.

Parity: python/ray/_private/usage/usage_lib.py — the reference collects
cluster metadata + library-usage tags into GCS KV under a usage prefix,
then a head-node thread periodically serializes a report. This runtime
keeps the same shape minus egress (none exists here): libraries call
``record_library_usage``/``record_extra_usage_tag`` which land in hub
KV; ``get_usage_report``/``write_usage_report`` aggregate them with
cluster metadata into a JSON blob written under the session dir.

Disable with RAY_TPU_USAGE_STATS_ENABLED=0 (reference env:
RAY_USAGE_STATS_ENABLED).
"""

from __future__ import annotations

import json
import os
import platform
import time
from typing import Any, Dict, List

_KV_LIB_PREFIX = b"__usage_lib:"
_KV_TAG_PREFIX = b"__usage_tag:"

# Recorded before init(): buffered locally, flushed on first connect
# (reference: usage_lib.py module-level _recorded_library_usages set).
_pending_libs: List[str] = []
_pending_tags: Dict[str, str] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1").lower() not in (
        "0",
        "false",
        "no",
    )


def _client_or_none():
    from . import worker

    if not worker.is_initialized():
        return None
    try:
        return worker.get_client()
    except Exception:
        return None


def record_library_usage(name: str) -> None:
    """Called by library __init__ (data/train/tune/serve/rllib/llm)."""
    if not usage_stats_enabled():
        return
    client = _client_or_none()
    if client is None:
        if name not in _pending_libs:
            _pending_libs.append(name)
        return
    try:
        client.kv_put(_KV_LIB_PREFIX + name.encode(), b"1", overwrite=True)
    except Exception:
        pass


def record_extra_usage_tag(key: str, value: str) -> None:
    if not usage_stats_enabled():
        return
    client = _client_or_none()
    if client is None:
        _pending_tags[key] = value
        return
    try:
        client.kv_put(_KV_TAG_PREFIX + key.encode(), value.encode(), overwrite=True)
    except Exception:
        pass


def flush_pending() -> None:
    """Re-record anything buffered before init (called from init())."""
    libs, _pending_libs[:] = list(_pending_libs), []
    tags = dict(_pending_tags)
    _pending_tags.clear()
    for name in libs:
        record_library_usage(name)
    for k, v in tags.items():
        record_extra_usage_tag(k, v)


def get_usage_report() -> Dict[str, Any]:
    """Aggregate cluster metadata + recorded tags (usage_lib.py
    generate_report_data parity)."""
    from . import worker

    client = worker.get_client()
    libs = sorted(
        k[len(_KV_LIB_PREFIX):].decode()
        for k in client.kv_keys(_KV_LIB_PREFIX)
    )
    tags = {}
    for k in client.kv_keys(_KV_TAG_PREFIX):
        val = client.kv_get(k)
        if val is not None:
            tags[k[len(_KV_TAG_PREFIX):].decode()] = val.decode()
    nodes = worker.nodes()
    total = worker.cluster_resources()
    return {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "collect_timestamp_ms": int(time.time() * 1000),
        "os": platform.system().lower(),
        "python_version": platform.python_version(),
        "total_num_nodes": len(nodes),
        "total_num_cpus": int(total.get("CPU", 0)),
        "total_num_tpus": int(total.get("TPU", 0)),
        "library_usages": libs,
        "extra_usage_tags": tags,
    }


def write_usage_report(session_dir: str) -> str:
    """Serialize the report under the session dir (the reference writes
    usage_stats.json on the head node before any export attempt)."""
    path = os.path.join(session_dir, "usage_stats.json")
    with open(path, "w") as f:
        json.dump(get_usage_report(), f, indent=2, sort_keys=True)
    return path
