"""Job submission: run driver entrypoints on the cluster.

Parity: python/ray/job_submission/ + dashboard/modules/job/
(job_manager.py:60 submit_job, job_supervisor.py:55 JobSupervisor) —
a detached named manager actor owns job lifecycle: each job's
entrypoint shell command runs as a subprocess of a supervisor with the
job's runtime env applied and RAY_TPU_ADDRESS pointing at this cluster,
so `ray_tpu.init()` inside the job connects instead of starting a new
runtime. Logs are captured per job; statuses follow the reference's
PENDING/RUNNING/SUCCEEDED/FAILED/STOPPED machine.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

JOB_MANAGER_NAME = "_ray_tpu_job_manager"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = {SUCCEEDED, FAILED, STOPPED}


class _JobManager:
    """Named actor: job table + one supervisor thread per job."""

    def __init__(self):
        import threading

        self._jobs: Dict[str, dict] = {}
        self._procs: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def submit(
        self,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        quota: Optional[dict] = None,
    ) -> str:
        import os
        import subprocess
        import tempfile
        import threading

        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            log_path = os.path.join(
                tempfile.gettempdir(), f"ray_tpu_job_{job_id}.log"
            )
            self._jobs[job_id] = {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "status": JobStatus.PENDING,
                "metadata": metadata or {},
                "log_path": log_path,
                "returncode": None,
            }

        def run():
            env = dict(os.environ)
            # the job's driver connects to THIS cluster
            env["RAY_TPU_ADDRESS"] = os.environ.get("RAY_TPU_HUB_ADDR", "")
            if tenant is not None or priority is not None or quota is not None:
                # multi-tenant scheduling handoff: the entrypoint's
                # init() reads RAY_TPU_JOB_* and registers with the
                # hub's fairsched engine under this identity
                from .job_config import JobConfig

                env.update(
                    JobConfig(
                        tenant=tenant or "default",
                        priority=priority or 0,
                        quota=quota,
                        job_id=job_id,
                    ).env_vars()
                )
            cwd = None
            renv = runtime_env or {}
            for k, v in (renv.get("env_vars") or {}).items():
                env[str(k)] = str(v)
            if renv.get("working_dir"):
                cwd = renv["working_dir"]
            with open(log_path, "wb") as logf:
                try:
                    proc = subprocess.Popen(
                        entrypoint, shell=True, env=env, cwd=cwd,
                        stdout=logf, stderr=subprocess.STDOUT,
                    )
                except OSError as e:
                    with self._lock:
                        self._jobs[job_id]["status"] = JobStatus.FAILED
                        self._jobs[job_id]["message"] = str(e)
                    return
                with self._lock:
                    self._jobs[job_id]["status"] = JobStatus.RUNNING
                    self._procs[job_id] = proc
                code = proc.wait()
            with self._lock:
                job = self._jobs[job_id]
                job["returncode"] = code
                if job["status"] != JobStatus.STOPPED:
                    job["status"] = (
                        JobStatus.SUCCEEDED if code == 0 else JobStatus.FAILED
                    )
                self._procs.pop(job_id, None)

        threading.Thread(target=run, daemon=True, name=f"job-{job_id}").start()
        return job_id

    def status(self, job_id: str) -> dict:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                raise ValueError(f"no such job {job_id}")
            return dict(job)

    def list_jobs(self) -> List[dict]:
        with self._lock:
            return [dict(j) for j in self._jobs.values()]

    def logs(self, job_id: str) -> str:
        info = self.status(job_id)
        try:
            with open(info["log_path"], "r", errors="replace") as f:
                return f.read()
        except OSError:
            return ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            job = self._jobs.get(job_id)
            if job is None:
                raise ValueError(f"no such job {job_id}")
            if proc is None:
                return False
            job["status"] = JobStatus.STOPPED
        try:
            proc.terminate()
        except Exception:
            pass
        return True


class JobSubmissionClient:
    """SDK over the manager actor (reference: job_submission.JobSubmissionClient)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, ignore_reinit_error=True)
        self._ray = ray_tpu
        try:
            self._mgr = ray_tpu.get_actor(JOB_MANAGER_NAME)
        except ValueError:
            try:
                mgr_cls = ray_tpu.remote(_JobManager)
                self._mgr = mgr_cls.options(
                    name=JOB_MANAGER_NAME, lifetime="detached", num_cpus=0
                ).remote()
                ray_tpu.get(self._mgr.__ray_ready__())
            except ValueError:
                # lost the creation race: someone else owns the name
                self._mgr = ray_tpu.get_actor(JOB_MANAGER_NAME)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        quota: Optional[dict] = None,
    ) -> str:
        return self._ray.get(
            self._mgr.submit.remote(
                entrypoint, submission_id, runtime_env, metadata,
                tenant, priority, quota,
            )
        )

    def get_job_status(self, job_id: str) -> str:
        return self._ray.get(self._mgr.status.remote(job_id))["status"]

    def get_job_info(self, job_id: str) -> dict:
        return self._ray.get(self._mgr.status.remote(job_id))

    def get_job_logs(self, job_id: str) -> str:
        return self._ray.get(self._mgr.logs.remote(job_id))

    def list_jobs(self) -> List[dict]:
        return self._ray.get(self._mgr.list_jobs.remote())

    def stop_job(self, job_id: str) -> bool:
        return self._ray.get(self._mgr.stop.remote(job_id))

    def wait_until_finished(self, job_id: str, timeout: float = 60.0) -> str:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} still {status} after {timeout}s")
