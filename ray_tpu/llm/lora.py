"""LoRA adapters for served models.

Parity: python/ray/llm LoRA multiplexing (serve deployments load
adapters on demand from `dynamic_lora_loading_path` and route requests
by adapter id through serve's model multiplexing). TPU-native
difference: adapters are FOLDED into the weights at load time
(W' = W + scale * A@B) and the folded model runs as its own engine —
XLA recompiles nothing (same shapes), decode batches stay uniform, and
the fold is one einsum per adapted matrix at load.

Adapter file format (.npz): for each adapted parameter, either
  "<path>.delta"            full-shape delta tensor, or
  "<path>.A" + "<path>.B"   factored (prod(leading_dims), r) x (r, last)
with "<path>" the '/'-joined pytree path (e.g. "blocks/wq",
"lm_head"). Optional scalar "scale" overrides the caller's scale.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def load_lora_adapter(path: str) -> Dict[str, np.ndarray]:
    """Read an adapter .npz into {key: array}."""
    return dict(np.load(path))


def _flatten(params, prefix=""):
    out = {}
    if isinstance(params, dict):
        for k, v in params.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = params
    return out


def apply_lora(params: Dict[str, Any], adapter: Dict[str, np.ndarray],
               scale: float = 1.0) -> Dict[str, Any]:
    """Fold an adapter into a COPY of params (unadapted leaves are
    shared, not copied)."""
    import jax.numpy as jnp

    if "scale" in adapter:
        scale = float(adapter["scale"])
    # group adapter entries by target path
    deltas: Dict[str, Any] = {}
    for key, arr in adapter.items():
        if key == "scale":
            continue
        if key.endswith(".delta"):
            deltas[key[:-6]] = ("delta", arr)
        elif key.endswith(".A"):
            path = key[:-2]
            b = adapter.get(path + ".B")
            if b is None:
                raise ValueError(f"adapter has {key} but no {path}.B")
            deltas[path] = ("ab", arr, b)
        elif key.endswith(".B"):
            if adapter.get(key[:-2] + ".A") is None:
                raise ValueError(f"adapter has {key} but no {key[:-2]}.A")
        else:
            raise ValueError(
                f"unrecognized adapter entry {key!r} "
                "(expected <path>.delta or <path>.A/.B)"
            )

    flat = _flatten(params)
    for path in deltas:
        if path not in flat:
            raise ValueError(
                f"adapter targets unknown parameter {path!r}; "
                f"known: {sorted(flat)[:8]}..."
            )

    def fold(node, prefix=""):
        if isinstance(node, dict):
            return {k: fold(v, f"{prefix}{k}/") for k, v in node.items()}
        path = prefix[:-1]
        spec = deltas.get(path)
        if spec is None:
            return node  # shared leaf, no copy
        if spec[0] == "delta":
            return node + scale * jnp.asarray(spec[1], node.dtype)
        _, a, b = spec
        delta = (jnp.asarray(a, jnp.float32) @ jnp.asarray(b, jnp.float32))
        return node + scale * delta.reshape(node.shape).astype(node.dtype)

    return fold(params)
