"""LLM deployment configuration + TP x PP placement sizing.

Parity: python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:123-142 — the reference sizes a placement group from the
engine's tensor/pipeline parallelism (PACK when pp==1, SPREAD with one
bundle per pp rank otherwise). Here the framework owns that natively:
``placement_bundles()`` returns the bundles + strategy the serve
deployment (or a batch-inference actor pool) reserves.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class LLMConfig:
    """Declarative model+engine config for serving / batch inference."""

    model_id: str = "base"            # name openai-style bodies use for
    # the base model ({"model": model_id} routes to base, not a LoRA)
    model_config: Any = None          # ray_tpu.models.llama.LlamaConfig
    checkpoint_path: Optional[str] = None  # orbax/npz dir; None = random init
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    max_batch_size: int = 8
    max_seq_len: int = 512
    accelerator_type: str = "TPU"
    # engine extras (temperature defaults etc.)
    engine_kwargs: Dict[str, Any] = field(default_factory=dict)
    # LoRA multiplexing (reference: ray.llm LoraConfig):
    #   {"dynamic_lora_loading_path": dir with <adapter_id>.npz,
    #    "max_adapters_per_replica": 4, "scale": 1.0}
    lora_config: Optional[Dict[str, Any]] = None

    def placement_bundles(self) -> Tuple[List[Dict[str, float]], str]:
        """(bundles, strategy): one bundle of tp chips per pp rank.

        pp == 1  -> single PACK bundle with tp chips (one host, ICI).
        pp  > 1  -> SPREAD, one tp-chip bundle per pipeline stage —
        stages ride DCN between hosts, tensor parallelism stays on-host
        ICI (the reference's PACK-vs-SPREAD split, vllm_models.py:131).
        """
        tp = self.tensor_parallel_size
        pp = self.pipeline_parallel_size
        res_key = self.accelerator_type if self.accelerator_type else "TPU"
        if pp == 1:
            return [{res_key: float(tp), "CPU": 1.0}], "PACK"
        return (
            [{res_key: float(tp), "CPU": 1.0} for _ in range(pp)],
            "SPREAD",
        )

    def load_params(self):
        """Materialize model params: from checkpoint_path if given
        (orbax dir or .npz), else fresh initialization."""
        import jax

        from ray_tpu.models import llama

        cfg = self.model_config or llama.LLAMA_TINY
        if not self.checkpoint_path:
            return llama.init_params(jax.random.PRNGKey(0), cfg)
        import os

        if self.checkpoint_path.endswith(".npz"):
            import numpy as np

            flat = dict(np.load(self.checkpoint_path))
            return _unflatten(flat)
        # orbax checkpoint dir (the Train stack's format,
        # train/_checkpoint.py)
        import orbax.checkpoint as ocp

        target = llama.init_params(jax.random.PRNGKey(0), cfg)
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(os.path.abspath(self.checkpoint_path), target)


def save_params_npz(params, path: str) -> None:
    """Flat .npz export (portable mini-format for tests/examples)."""
    import numpy as np

    flat = _flatten(params)
    np.savez(path, **{k: np.asarray(v) for k, v in flat.items()})


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten(flat):
    root: dict = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return _listify(root)


def _listify(node):
    if not isinstance(node, dict):
        return node
    if node and all(k.isdigit() for k in node):
        return [_listify(node[k]) for k in sorted(node, key=int)]
    return {k: _listify(v) for k, v in node.items()}
