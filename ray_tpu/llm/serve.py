"""LLM serving: continuous-batching deployment over ray_tpu.serve.

Parity: python/ray/llm/_internal/serve/deployments/llm/ (VLLMService +
build_openai_app) re-designed TPU-native — the engine is the in-tree
Llama with an XLA KV cache (llm/_internal/engine.py), not a wrapped
vLLM; requests stream tokens through the serve streaming-response path
(handle.options(stream=True) over num_returns="streaming").

HTTP: `serve.run(build_llm_app(cfg))` exposes POST /<name> with JSON
{"prompt_ids": [...], "max_tokens": N, "temperature": t, "stream": bool}
via the existing serve proxy.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from .config import LLMConfig


class LLMServer:
    """Deployment class: one engine + a background continuous-batching
    loop; concurrent callers enqueue and stream tokens out."""

    def __init__(self, llm_config: LLMConfig):
        self.config = llm_config
        params = llm_config.load_params()
        from ._internal.engine import LlamaEngine

        from ray_tpu.models import llama

        self.engine = LlamaEngine(
            llm_config.model_config or llama.LLAMA_TINY,
            params,
            max_batch=llm_config.max_batch_size,
            max_seq=llm_config.max_seq_len,
            **llm_config.engine_kwargs,
        )
        self._pending: "queue.Queue" = queue.Queue()
        self._id_counter = itertools.count()
        self._token_queues: Dict[str, "queue.Queue"] = {}
        self._lock = threading.Lock()
        self._running = True
        self._loop_thread = threading.Thread(
            target=self._batching_loop, daemon=True, name="llm-batching"
        )
        self._loop_thread.start()

    # -- continuous batching loop -------------------------------------
    def _batching_loop(self):
        while self._running:
            # admit as many pending requests as there are free slots
            admitted = False
            while self.engine.has_capacity():
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                q = self._token_queues.get(req.request_id)
                try:
                    self.engine.add_request(req)
                except Exception as e:
                    # a bad request (e.g. prompt >= max_seq) must fail
                    # its own caller, never the batching thread
                    if q is not None:
                        q.put(("error", e))
                    continue
                admitted = True
                # prefill may already finish the request (max_tokens=1)
                if q is not None:
                    q.put(("token", req.generated[0]))
                    if req.done:
                        q.put(("done", None))
            if self.engine.num_active():
                try:
                    emitted = self.engine.step()
                except Exception as e:
                    # engine fault: fail every active request, keep serving
                    for slot in list(self.engine.active):
                        req = self.engine.active[slot]
                        q = self._token_queues.get(req.request_id)
                        if q is not None:
                            q.put(("error", e))
                        self.engine._finish(slot)
                    continue
                for req, tok in emitted:
                    q = self._token_queues.get(req.request_id)
                    if q is not None:
                        q.put(("token", tok))
                        if req.done:
                            q.put(("done", None))
            elif not admitted:
                time.sleep(0.005)

    # -- request entrypoints ------------------------------------------
    def generate_stream(
        self,
        prompt_ids: List[int],
        max_tokens: int = 64,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
    ):
        """Generator: yields token ids as the engine produces them
        (invoked through serve's streaming path)."""
        from ._internal.engine import GenRequest

        rid = f"req{next(self._id_counter)}"
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._token_queues[rid] = q
        self._pending.put(
            GenRequest(
                request_id=rid,
                prompt_ids=list(prompt_ids),
                max_tokens=max_tokens,
                temperature=temperature,
                eos_id=eos_id,
            )
        )
        try:
            while True:
                kind, tok = q.get(timeout=120)
                if kind == "done":
                    return
                if kind == "error":
                    raise tok
                yield tok
        finally:
            with self._lock:
                self._token_queues.pop(rid, None)

    def generate(self, prompt_ids, max_tokens=64, temperature=0.0,
                 eos_id=None) -> List[int]:
        return list(
            self.generate_stream(prompt_ids, max_tokens, temperature, eos_id)
        )

    def __call__(self, request: Dict[str, Any]):
        """Entrypoint for both direct handle calls ({"prompt_ids": ...})
        and the serve HTTP proxy (request dict with a raw JSON body)."""
        if "prompt_ids" not in request and request.get("body"):
            import json

            request = json.loads(request["body"])
        prompt_ids = request.get("prompt_ids")
        if prompt_ids is None:
            raise ValueError("request must contain 'prompt_ids'")
        toks = self.generate(
            prompt_ids,
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
        )
        return {"token_ids": toks, "num_generated": len(toks)}

    def engine_stats(self) -> Dict[str, Any]:
        return {
            "active": self.engine.num_active(),
            "free_slots": len(self.engine.free_slots),
            "max_batch": self.engine.max_batch,
        }


def build_llm_app(llm_config: LLMConfig, name: str = "llm"):
    """Bound deployment for `serve.run` (reference: build_openai_app).
    Sizes actor resources from the TP x PP placement bundles."""
    from ray_tpu import serve

    bundles, strategy = llm_config.placement_bundles()
    # single-bundle (pp=1) deployments pin the whole gang's chips on the
    # replica actor; multi-bundle pp is reserved via a placement group by
    # the replica itself when it spins stage actors (future work: true
    # cross-host pp stages)
    num_tpus = bundles[0].get("TPU", 0) if llm_config.accelerator_type == "TPU" else 0
    deployment = serve.deployment(
        _LLMServerWrapper,
        name=name,
        ray_actor_options={"num_tpus": num_tpus} if num_tpus else None,
    )
    return deployment.bind(llm_config)


class _LLMServerWrapper(LLMServer):
    """Deployment wrapper (serve.deployment needs a fresh class so user
    code can also subclass LLMServer directly)."""
