"""LLM serving: continuous-batching deployment over ray_tpu.serve.

Parity: python/ray/llm/_internal/serve/deployments/llm/ (VLLMService +
build_openai_app) re-designed TPU-native — the engine is the in-tree
Llama with an XLA KV cache (llm/_internal/engine.py), not a wrapped
vLLM; requests stream tokens through the serve streaming-response path
(handle.options(stream=True) over num_returns="streaming").

HTTP: `serve.run(build_llm_app(cfg))` exposes POST /<name> with JSON
{"prompt_ids": [...], "max_tokens": N, "temperature": t, "stream": bool}
via the existing serve proxy.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from .config import LLMConfig


class LLMServer:
    """Deployment class: one engine + a background continuous-batching
    loop; concurrent callers enqueue and stream tokens out."""

    def __init__(self, llm_config: LLMConfig):
        self.config = llm_config
        params = llm_config.load_params()
        from ._internal.engine import LlamaEngine

        from ray_tpu.models import llama

        self._base_params = params
        self._model_config = llm_config.model_config or llama.LLAMA_TINY
        self.engine = LlamaEngine(
            self._model_config,
            params,
            max_batch=llm_config.max_batch_size,
            max_seq=llm_config.max_seq_len,
            **llm_config.engine_kwargs,
        )
        # LoRA multiplexing: adapter id -> folded-weights engine, LRU-
        # capped (never evicting active engines — which is why this is
        # a hand-rolled cache rather than @serve.multiplexed); loaded
        # ids ride the serve multiplex registry so the router prefers
        # replicas already holding an adapter
        from collections import OrderedDict

        self._engines: "OrderedDict[str, LlamaEngine]" = OrderedDict()
        self._loading: set = set()  # adapter ids mid-cold-load (cap slots)
        self._engines[""] = self.engine
        self._engines_lock = threading.Lock()
        self._reporter = None
        if llm_config.lora_config:
            from ray_tpu.serve.multiplex import register_model_reporter

            self._reporter = register_model_reporter(self._loaded_adapters)
        self._pending: "queue.Queue" = queue.Queue()
        self._id_counter = itertools.count()
        self._token_queues: Dict[str, "queue.Queue"] = {}
        self._lock = threading.Lock()
        self._running = True
        self._loop_thread = threading.Thread(
            target=self._batching_loop, daemon=True, name="llm-batching"
        )
        self._loop_thread.start()

    # -- LoRA engines --------------------------------------------------
    def _loaded_adapters(self):
        with self._engines_lock:
            return [aid for aid in self._engines if aid]

    def shutdown(self) -> None:
        """Stop the batching loop and drop the multiplex registration.
        Must be called explicitly for in-process servers: the batching
        thread and the multiplex registry hold strong refs, so __del__
        would never fire (Serve replicas die with their actor process,
        which achieves the same)."""
        self._running = False
        if self._reporter is not None:
            from ray_tpu.serve.multiplex import unregister_model_reporter

            unregister_model_reporter(self._reporter)
            self._reporter = None

    def _engine_for(self, adapter_id: str):
        """Engine serving this adapter, loading + folding on first use
        (LRU-capped per lora_config.max_adapters_per_replica).

        Callers invoke this at SUBMISSION time (their own thread) so a
        cold load — disk read + fold + KV-cache alloc + first XLA
        compile — never stalls the batching loop's token emission for
        other requests; the loop only re-resolves on the rare
        submitted-then-evicted race."""
        with self._engines_lock:
            eng = self._engines.get(adapter_id)
            if eng is not None:
                self._engines.move_to_end(adapter_id)
                return eng
        lora = self.config.lora_config
        if not lora:
            raise ValueError(
                f"request for adapter {adapter_id!r} but no lora_config"
            )
        import os

        if (
            not adapter_id
            or "/" in adapter_id
            or "\\" in adapter_id
            or ".." in adapter_id
        ):
            # the id comes from request bodies: it must never be able to
            # escape dynamic_lora_loading_path
            raise ValueError(f"invalid adapter id {adapter_id!r}")

        cap = int(lora.get("max_adapters_per_replica", 4))
        with self._engines_lock:
            # HARD cap: when every loaded adapter is mid-generation and
            # the cap is reached, refuse — an unbounded engine pile-up
            # (full KV cache each) OOMs the replica. In-flight loads
            # count via the _loading placeholder set, closing the
            # check-then-act window (the load itself runs unlocked for
            # seconds).
            busy = [
                aid for aid in self._engines
                if aid and self._engines[aid].num_active()
            ]
            if len(busy) + len(self._loading) >= cap:
                raise RuntimeError(
                    f"all {cap} adapter slots are busy; retry later "
                    "(max_adapters_per_replica)"
                )
            self._loading.add(adapter_id)

        try:
            from ._internal.engine import LlamaEngine
            from .lora import apply_lora, load_lora_adapter

            base = lora["dynamic_lora_loading_path"]
            path = (
                base.format(adapter_id)
                if "{}" in base
                else os.path.join(base, adapter_id + ".npz")
            )
            folded = apply_lora(
                self._base_params,
                load_lora_adapter(path),
                scale=float(lora.get("scale", 1.0)),
            )
            eng = LlamaEngine(
                self._model_config,
                folded,
                max_batch=self.config.max_batch_size,
                max_seq=self.config.max_seq_len,
                **self.config.engine_kwargs,
            )
        finally:
            with self._engines_lock:
                self._loading.discard(adapter_id)
        with self._engines_lock:
            existing = self._engines.get(adapter_id)
            if existing is not None:  # lost a racing load of the same id
                return existing
            self._engines[adapter_id] = eng
            # LRU-evict idle adapters past the cap — never the base "",
            # never an engine mid-generation, never the one just loaded
            evictable = [
                aid for aid in self._engines
                if aid and aid != adapter_id
                and not self._engines[aid].num_active()
            ]
            while len(self._engines) - 1 > cap and evictable:
                del self._engines[evictable.pop(0)]
        return eng

    # -- continuous batching loop -------------------------------------
    def _batching_loop(self):
        while self._running:
            # admit as many pending requests as their engines have slots
            admitted = False
            requeue = []
            while True:
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                q = self._token_queues.get(req.request_id)
                try:
                    eng = self._engine_for(req.adapter_id)
                except Exception as e:
                    if q is not None:
                        q.put(("error", e))
                    continue
                if not eng.has_capacity():
                    requeue.append(req)
                    continue
                try:
                    ok = eng.add_request(req)
                except Exception as e:
                    # a bad request (e.g. prompt >= max_seq) must fail
                    # its own caller, never the batching thread
                    if q is not None:
                        q.put(("error", e))
                    continue
                if ok is False:
                    # no slot after all (has_capacity raced a concurrent
                    # admit): retry next loop instead of dropping the
                    # request on the floor
                    requeue.append(req)
                    continue
                admitted = True
                # the first token arrives from step() once the chunked
                # prefill completes — nothing to emit at admission
            for req in requeue:
                self._pending.put(req)
            stepped = False
            with self._engines_lock:
                live_engines = list(self._engines.values())
            for eng in live_engines:
                if not eng.num_active():
                    continue
                stepped = True
                try:
                    emitted = eng.step()
                except Exception as e:
                    # engine fault: fail every in-flight request, keep serving
                    for req in eng.abort_all():
                        q = self._token_queues.get(req.request_id)
                        if q is not None:
                            q.put(("error", e))
                    continue
                for req, tok in emitted:
                    q = self._token_queues.get(req.request_id)
                    if q is not None:
                        q.put(("token", tok))
                        if req.done:
                            q.put(("done", None))
            if not stepped and not admitted:
                time.sleep(0.005)

    # -- request entrypoints ------------------------------------------
    def generate_stream(
        self,
        prompt_ids: List[int],
        max_tokens: int = 64,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        adapter_id: Optional[str] = None,
    ):
        """Generator: yields token ids as the engine produces them
        (invoked through serve's streaming path)."""
        from ._internal.engine import GenRequest

        if adapter_id is None:
            # serve routing: handle.options(multiplexed_model_id=...)
            from ray_tpu.serve import get_multiplexed_model_id

            adapter_id = get_multiplexed_model_id()
        if adapter_id:
            # cold-load in THIS thread (see _engine_for docstring): load
            # errors also surface here, at submission, with a stack
            self._engine_for(adapter_id)
        rid = f"req{next(self._id_counter)}"
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._token_queues[rid] = q
        self._pending.put(
            GenRequest(
                request_id=rid,
                prompt_ids=list(prompt_ids),
                max_tokens=max_tokens,
                temperature=temperature,
                eos_id=eos_id,
                adapter_id=adapter_id or "",
            )
        )
        try:
            while True:
                kind, tok = q.get(timeout=120)
                if kind == "done":
                    return
                if kind == "error":
                    raise tok
                yield tok
        finally:
            with self._lock:
                self._token_queues.pop(rid, None)

    def generate(self, prompt_ids, max_tokens=64, temperature=0.0,
                 eos_id=None, adapter_id=None) -> List[int]:
        return list(
            self.generate_stream(
                prompt_ids, max_tokens, temperature, eos_id, adapter_id
            )
        )

    def __call__(self, request: Dict[str, Any]):
        """Entrypoint for both direct handle calls ({"prompt_ids": ...})
        and the serve HTTP proxy (request dict with a raw JSON body)."""
        if "prompt_ids" not in request and request.get("body"):
            import json

            request = json.loads(request["body"])
        prompt_ids = request.get("prompt_ids")
        if prompt_ids is None:
            raise ValueError("request must contain 'prompt_ids'")
        # "model" in the body (openai-style) beats routing context
        model = self._resolve_adapter(request)
        toks = self.generate(
            prompt_ids,
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
            adapter_id=model,
        )
        return {"token_ids": toks, "num_generated": len(toks)}

    def _resolve_adapter(self, request: Dict[str, Any]) -> Optional[str]:
        """'model' in a request body -> adapter id: the base model's own
        name (model_id) or "" routes to the base engine; anything else
        is a LoRA adapter id (reference ray.llm routing semantics).
        None = no field, fall back to the serve routing context."""
        model = request.get("model")
        if model is not None and model in ("", self.config.model_id):
            return ""
        return model

    def engine_stats(self) -> Dict[str, Any]:
        return {
            "active": self.engine.num_active(),
            "free_slots": sum(
                len(s.free_slots) for s in self.engine.shards
            ),
            "max_batch": self.engine.max_batch,
            "shards": len(self.engine.shards),
        }


def build_llm_app(llm_config: LLMConfig, name: str = "llm", server_cls=None):
    """Bound deployment for `serve.run` (reference: build_openai_app).
    Sizes actor resources from the TP x PP placement bundles."""
    from ray_tpu import serve

    bundles, strategy = llm_config.placement_bundles()
    # single-bundle (pp=1) deployments pin the whole gang's chips on the
    # replica actor; multi-bundle pp is reserved via a placement group by
    # the replica itself when it spins stage actors (future work: true
    # cross-host pp stages)
    num_tpus = bundles[0].get("TPU", 0) if llm_config.accelerator_type == "TPU" else 0
    deployment = serve.deployment(
        server_cls or _LLMServerWrapper,
        name=name,
        ray_actor_options={"num_tpus": num_tpus} if num_tpus else None,
    )
    return deployment.bind(llm_config)


class _LLMServerWrapper(LLMServer):
    """Deployment wrapper (serve.deployment needs a fresh class so user
    code can also subclass LLMServer directly)."""


class OpenAIServer(LLMServer):
    """OpenAI-style completions surface (reference: build_openai_app's
    router deployments). Accepts completion bodies:

        {"model": "<model_id or lora adapter id>",
         "prompt": [token ids] (or "prompt_ids"),
         "max_tokens": N, "temperature": t}

    and answers {"object": "text_completion", "model": ...,
    "choices": [{"token_ids": [...], "index": 0,
    "finish_reason": "length"|"stop"}], "usage": {...}}. Token-id in/out:
    tokenization happens client-side (there is no tokenizer dependency
    in-tree)."""

    def __call__(self, request: Dict[str, Any]):
        import json

        if "prompt" not in request and "prompt_ids" not in request and request.get("body"):
            request = json.loads(request["body"])
        prompt_ids = request.get("prompt_ids") or request.get("prompt")
        if not isinstance(prompt_ids, list):
            raise ValueError(
                "completion request needs 'prompt' (token-id list)"
            )
        adapter = self._resolve_adapter(request)
        max_tokens = int(request.get("max_tokens", 64))
        eos_id = request.get("eos_id")
        toks = self.generate(
            prompt_ids,
            max_tokens=max_tokens,
            temperature=float(request.get("temperature", 0.0)),
            eos_id=eos_id,
            adapter_id=adapter,
        )
        # "stop" ONLY on an eos match; anything else — max_tokens hit or
        # the engine's max_seq context truncation — is "length"
        finish = (
            "stop"
            if eos_id is not None and toks and toks[-1] == eos_id
            else "length"
        )
        return {
            "object": "text_completion",
            "model": adapter or self.config.model_id,
            "choices": [
                {"index": 0, "token_ids": toks, "finish_reason": finish}
            ],
            "usage": {
                "prompt_tokens": len(prompt_ids),
                "completion_tokens": len(toks),
                "total_tokens": len(prompt_ids) + len(toks),
            },
        }


def build_openai_app(llm_config: LLMConfig, name: str = "v1-completions"):
    """Bound OpenAI-compatible completions app (reference:
    ray.llm build_openai_app); serve with
    ``serve.run(app, route_prefix="/v1/completions")``."""
    return build_llm_app(llm_config, name=name, server_cls=OpenAIServer)
