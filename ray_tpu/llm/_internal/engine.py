"""LlamaEngine: TPU-native generation with continuous batching.

The role vLLM plays for the reference's ray.llm
(reference: python/ray/llm/_internal/serve/deployments/llm/vllm/) —
re-designed for XLA instead of wrapped:

- Slot-based continuous batching: a fixed ``max_batch`` of cache slots;
  every decode step advances ALL active slots in one jitted (B, 1)
  program (static shapes; no recompiles as requests come and go).
- Prefill runs per-request at power-of-two bucket lengths, writing the
  prompt into the slot's cache rows; a handful of bucket sizes bounds
  total compilations.
- KV cache is preallocated (L, B, max_seq, KVH, hd); per-slot lengths
  mask attention (models/llama.py forward_with_cache).
- Sampling (greedy / temperature) is jitted with the decode step.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class GenRequest:
    request_id: str
    prompt_ids: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    adapter_id: str = ""  # LoRA adapter ("" = base model)
    # filled during generation
    slot: int = -1
    generated: List[int] = field(default_factory=list)
    done: bool = False


class LlamaEngine:
    def __init__(
        self,
        config,
        params,
        *,
        max_batch: int = 8,
        max_seq: int = 512,
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self.config = config
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.cache = llama.init_kv_cache(config, max_batch, max_seq)
        self.lengths = np.zeros(max_batch, dtype=np.int32)  # tokens in cache
        self.free_slots = list(range(max_batch))
        self.active: Dict[int, GenRequest] = {}  # slot -> request
        self._rng = jax.random.PRNGKey(seed)
        self._jax = jax
        self._jnp = jnp
        self._llama = llama

        # prefill buckets: powers of two up to max_seq
        self.buckets = []
        b = 16
        while b < max_seq:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(max_seq)

        @partial(jax.jit, static_argnames=("bucket",))
        def prefill(params, cache, tokens, slot_onehot, start, length, bucket):
            # tokens (1, bucket) padded; writes into the slot's rows and
            # returns logits at the prompt's last real token
            del bucket
            logits, new_cache = llama.forward_with_cache(
                params, tokens, cache_slice(cache, slot_onehot), start, config
            )
            new_cache = cache_merge(cache, new_cache, slot_onehot)
            last = logits[0, length - 1]
            return last, new_cache

        def cache_slice(cache, slot_onehot):
            # gather the single slot (1, S, KVH, hd) per layer
            idx = jnp.argmax(slot_onehot)
            return {
                "k": jax.lax.dynamic_slice_in_dim(cache["k"], idx, 1, axis=1),
                "v": jax.lax.dynamic_slice_in_dim(cache["v"], idx, 1, axis=1),
            }

        def cache_merge(cache, updated, slot_onehot):
            idx = jnp.argmax(slot_onehot)
            return {
                "k": jax.lax.dynamic_update_slice_in_dim(
                    cache["k"], updated["k"], idx, axis=1
                ),
                "v": jax.lax.dynamic_update_slice_in_dim(
                    cache["v"], updated["v"], idx, axis=1
                ),
            }

        @jax.jit
        def decode(params, cache, last_tokens, lengths, temps, rng):
            # one token for every slot: tokens (B,), lengths (B,) = count
            # already in cache; inactive slots just waste a lane
            logits, new_cache = llama.forward_with_cache(
                params, last_tokens[:, None], cache, lengths, config
            )
            logits = logits[:, 0]  # (B, V)
            greedy = jnp.argmax(logits, axis=-1)
            keys = jax.random.split(rng, logits.shape[0] + 1)
            sampled = jax.vmap(
                lambda k, lg, t: jax.random.categorical(k, lg / jnp.maximum(t, 1e-4))
            )(keys[1:], logits, temps)
            toks = jnp.where(temps > 0, sampled, greedy)
            return toks.astype(jnp.int32), new_cache, keys[0]

        self._prefill = prefill
        self._decode = decode
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def has_capacity(self) -> bool:
        return bool(self.free_slots)

    def num_active(self) -> int:
        return len(self.active)

    def add_request(self, req: GenRequest) -> bool:
        """Admit a request into a free slot (prefill immediately)."""
        import numpy as np

        with self._lock:
            if not self.free_slots:
                return False
            if len(req.prompt_ids) >= self.max_seq:
                raise ValueError(
                    f"prompt length {len(req.prompt_ids)} >= max_seq {self.max_seq}"
                )
            slot = self.free_slots.pop()
            req.slot = slot
            n = len(req.prompt_ids)
            bucket = next(b for b in self.buckets if b >= n)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = req.prompt_ids
            onehot = np.zeros(self.max_batch, np.float32)
            onehot[slot] = 1.0
            last_logits, self.cache = self._prefill(
                self.params, self.cache, tokens, onehot,
                np.zeros(1, np.int32), n, bucket=bucket,
            )
            # first generated token comes from the prompt's last logits
            lg = np.asarray(last_logits)
            if req.temperature > 0:
                self._rng, sub = self._jax.random.split(self._rng)
                tok = int(self._jax.random.categorical(
                    sub, self._jnp.asarray(lg) / max(req.temperature, 1e-4)))
            else:
                tok = int(lg.argmax())
            req.generated.append(tok)
            self.lengths[slot] = n
            self.active[slot] = req
            if req.eos_id is not None and tok == req.eos_id:
                self._finish(slot)
            elif len(req.generated) >= req.max_tokens:
                self._finish(slot)
            return True

    def _finish(self, slot: int):
        req = self.active.pop(slot)
        req.done = True
        self.lengths[slot] = 0
        self.free_slots.append(slot)

    def step(self) -> List[Tuple[GenRequest, int]]:
        """One decode step for every active slot. Returns (request,
        new_token) pairs emitted this step (callers stream them out)."""
        import numpy as np

        with self._lock:
            if not self.active:
                return []
            last = np.zeros(self.max_batch, np.int32)
            temps = np.zeros(self.max_batch, np.float32)
            for slot, req in self.active.items():
                last[slot] = req.generated[-1]
                temps[slot] = req.temperature
            toks, self.cache, self._rng = self._decode(
                self.params, self.cache, last,
                self.lengths, temps, self._rng,
            )
            toks = np.asarray(toks)
            out = []
            for slot in list(self.active.keys()):
                req = self.active[slot]
                # the decode consumed the previous token: account it
                self.lengths[slot] += 1
                tok = int(toks[slot])
                req.generated.append(tok)
                out.append((req, tok))
                total_len = self.lengths[slot] + 1
                if (
                    (req.eos_id is not None and tok == req.eos_id)
                    or len(req.generated) >= req.max_tokens
                    or total_len >= self.max_seq - 1
                ):
                    self._finish(slot)
            return out

    # ------------------------------------------------------------------
    def generate(self, prompt_ids: List[int], *, max_tokens: int = 64,
                 temperature: float = 0.0, eos_id: Optional[int] = None
                 ) -> List[int]:
        """Synchronous single-prompt convenience (batch path: step())."""
        req = GenRequest(
            request_id="sync", prompt_ids=list(prompt_ids),
            max_tokens=max_tokens, temperature=temperature, eos_id=eos_id,
        )
        ok = self.add_request(req)
        assert ok, "engine full"
        while not req.done:
            self.step()
        return req.generated
