"""LlamaEngine: TPU-native generation with continuous batching.

The role vLLM plays for the reference's ray.llm
(reference: python/ray/llm/_internal/serve/deployments/llm/vllm/) —
re-designed for XLA instead of wrapped:

- Slot-based continuous batching: cache SHARDS of ``max_batch`` slots;
  every decode step advances one shard's active slots in one jitted
  (B, 1) program (static shapes; no recompiles as requests come and
  go). When all slots are busy the engine GROWS by allocating another
  shard — same compiled programs, more concurrent sequences — up to
  ``max_slots``.
- CHUNKED prefill: prompts enter the cache ``prefill_chunk`` tokens per
  engine step, interleaved with decode — a long prompt cannot stall
  the decode of already-running sequences (vLLM's chunked-prefill
  scheduler, reference llm/_internal/batch/stages/vllm_engine_stage.py
  wraps the same idea). Chunk buckets bound compilations.
- KV cache is preallocated per shard (L, B, max_seq, KVH, hd);
  per-slot lengths mask attention (models/llama.py forward_with_cache).
- Sampling (greedy / temperature) is jitted with the decode step.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class GenRequest:
    request_id: str
    prompt_ids: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    adapter_id: str = ""  # LoRA adapter ("" = base model)
    # filled during generation
    slot: int = -1
    shard: int = -1
    prefill_pos: int = 0  # prompt tokens already written to cache
    generated: List[int] = field(default_factory=list)
    done: bool = False


@dataclass
class _Shard:
    """One (B, max_seq) KV cache block plus its slot bookkeeping."""

    cache: Any
    lengths: np.ndarray
    free_slots: List[int]
    active: Dict[int, GenRequest] = field(default_factory=dict)
    prefilling: "deque[GenRequest]" = field(default_factory=deque)


class LlamaEngine:
    def __init__(
        self,
        config,
        params,
        *,
        max_batch: int = 8,
        max_seq: int = 512,
        seed: int = 0,
        prefill_chunk: int = 64,
        max_slots: Optional[int] = None,
    ):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self.config = config
        self.params = params
        self.max_batch = max_batch
        self.max_seq = max_seq
        # chunk must divide max_seq: chunk starts are then always
        # aligned and a padded chunk bucket can never run past the
        # cache end (dynamic_update_slice would CLAMP the start
        # backward and overwrite earlier valid rows)
        chunk = min(prefill_chunk, max_seq)
        while max_seq % chunk:
            chunk //= 2
        self.prefill_chunk = max(chunk, 1)
        # growth is whole-shard; round the cap to shard granularity so
        # the KV-memory bound it expresses actually holds
        want_slots = max_slots or 4 * max_batch
        self.max_slots = max(max_batch, (want_slots // max_batch) * max_batch)
        self._rng = jax.random.PRNGKey(seed)
        self._jax = jax
        self._jnp = jnp
        self._llama = llama
        self.shards: List[_Shard] = [self._new_shard()]

        # prefill-chunk buckets: powers of two up to prefill_chunk
        self.buckets = []
        b = 16
        while b < self.prefill_chunk:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(self.prefill_chunk)

        @partial(jax.jit, static_argnames=("bucket",))
        def prefill(params, cache, tokens, slot_onehot, start, length, bucket):
            # tokens (1, bucket) padded; writes into the slot's rows at
            # offset `start` and returns logits at the chunk's last real
            # token (used only when the chunk completes the prompt)
            del bucket
            logits, new_cache = llama.forward_with_cache(
                params, tokens, cache_slice(cache, slot_onehot), start, config
            )
            new_cache = cache_merge(cache, new_cache, slot_onehot)
            last = logits[0, length - 1]
            return last, new_cache

        def cache_slice(cache, slot_onehot):
            # gather the single slot (1, S, KVH, hd) per layer
            idx = jnp.argmax(slot_onehot)
            return {
                "k": jax.lax.dynamic_slice_in_dim(cache["k"], idx, 1, axis=1),
                "v": jax.lax.dynamic_slice_in_dim(cache["v"], idx, 1, axis=1),
            }

        def cache_merge(cache, updated, slot_onehot):
            idx = jnp.argmax(slot_onehot)
            return {
                "k": jax.lax.dynamic_update_slice_in_dim(
                    cache["k"], updated["k"], idx, axis=1
                ),
                "v": jax.lax.dynamic_update_slice_in_dim(
                    cache["v"], updated["v"], idx, axis=1
                ),
            }

        @jax.jit
        def decode(params, cache, last_tokens, lengths, temps, rng):
            # one token for every slot: tokens (B,), lengths (B,) = count
            # already in cache; inactive slots just waste a lane
            logits, new_cache = llama.forward_with_cache(
                params, last_tokens[:, None], cache, lengths, config
            )
            logits = logits[:, 0]  # (B, V)
            greedy = jnp.argmax(logits, axis=-1)
            keys = jax.random.split(rng, logits.shape[0] + 1)
            sampled = jax.vmap(
                lambda k, lg, t: jax.random.categorical(k, lg / jnp.maximum(t, 1e-4))
            )(keys[1:], logits, temps)
            toks = jnp.where(temps > 0, sampled, greedy)
            return toks.astype(jnp.int32), new_cache, keys[0]

        self._prefill = prefill
        self._decode = decode
        self._lock = threading.Lock()

    def _new_shard(self) -> _Shard:
        return _Shard(
            cache=self._llama.init_kv_cache(
                self.config, self.max_batch, self.max_seq
            ),
            lengths=np.zeros(self.max_batch, dtype=np.int32),
            free_slots=list(range(self.max_batch)),
        )

    # ------------------------------------------------------------------
    def has_capacity(self) -> bool:
        if any(s.free_slots for s in self.shards):
            return True
        return len(self.shards) * self.max_batch < self.max_slots

    def num_active(self) -> int:
        return sum(
            len(s.active) + len(s.prefilling) for s in self.shards
        )

    def in_flight_requests(self) -> List[GenRequest]:
        out: List[GenRequest] = []
        for s in self.shards:
            out.extend(s.active.values())
            out.extend(s.prefilling)
        return out

    def abort_all(self) -> List[GenRequest]:
        """Drop every in-flight request (engine fault path); returns
        them so the caller can fail their waiters."""
        with self._lock:
            dropped = self.in_flight_requests()
            for s in self.shards:
                for slot in list(s.active):
                    self._finish(s, slot)
                while s.prefilling:
                    req = s.prefilling.popleft()
                    req.done = True
                    s.lengths[req.slot] = 0
                    s.free_slots.append(req.slot)
            return dropped

    def add_request(self, req: GenRequest) -> bool:
        """Admit into a free slot. No model compute happens here — the
        prompt prefills chunk-by-chunk inside step(), interleaved with
        decode, so admission never stalls running sequences."""
        with self._lock:
            if len(req.prompt_ids) >= self.max_seq:
                raise ValueError(
                    f"prompt length {len(req.prompt_ids)} >= max_seq {self.max_seq}"
                )
            si = next(
                (i for i, s in enumerate(self.shards) if s.free_slots), None
            )
            if si is None:
                if len(self.shards) * self.max_batch >= self.max_slots:
                    return False
                self.shards.append(self._new_shard())  # slot growth
                si = len(self.shards) - 1
            shard = self.shards[si]
            req.slot = shard.free_slots.pop()
            req.shard = si
            req.prefill_pos = 0
            shard.prefilling.append(req)
            return True

    def _finish(self, shard: _Shard, slot: int):
        req = shard.active.pop(slot)
        req.done = True
        shard.lengths[slot] = 0
        shard.free_slots.append(slot)

    def _pump_prefill(self, shard: _Shard, out: List[Tuple[GenRequest, int]]):
        """Write ONE chunk of the oldest pending prompt into the cache;
        on prompt completion, sample the first token and activate the
        slot for decoding."""
        if not shard.prefilling:
            return
        req = shard.prefilling[0]
        n = len(req.prompt_ids)
        pos = req.prefill_pos
        chunk = min(self.prefill_chunk, n - pos)
        bucket = next(b for b in self.buckets if b >= chunk)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :chunk] = req.prompt_ids[pos:pos + chunk]
        onehot = np.zeros(self.max_batch, np.float32)
        onehot[req.slot] = 1.0
        last_logits, shard.cache = self._prefill(
            self.params, shard.cache, tokens, onehot,
            np.asarray([pos], np.int32), chunk, bucket=bucket,
        )
        req.prefill_pos = pos + chunk
        if req.prefill_pos < n:
            return
        # prompt complete: first generated token from the last logits
        shard.prefilling.popleft()
        lg = np.asarray(last_logits)
        if req.temperature > 0:
            self._rng, sub = self._jax.random.split(self._rng)
            tok = int(self._jax.random.categorical(
                sub, self._jnp.asarray(lg) / max(req.temperature, 1e-4)))
        else:
            tok = int(lg.argmax())
        req.generated.append(tok)
        shard.lengths[req.slot] = n
        shard.active[req.slot] = req
        out.append((req, tok))
        if (req.eos_id is not None and tok == req.eos_id) or (
            len(req.generated) >= req.max_tokens
        ):
            self._finish(shard, req.slot)

    def step(self) -> List[Tuple[GenRequest, int]]:
        """One engine step: per shard, one prefill chunk (if a prompt is
        pending) then one decode for every active slot. Returns
        (request, new_token) pairs emitted this step — the FIRST token
        of a request (sampled off its prefill) arrives here too."""
        with self._lock:
            out: List[Tuple[GenRequest, int]] = []
            for shard in self.shards:
                self._pump_prefill(shard, out)
                if not shard.active:
                    continue
                last = np.zeros(self.max_batch, np.int32)
                temps = np.zeros(self.max_batch, np.float32)
                # inactive lanes (free or mid-prefill) still ride the
                # batched decode; point their cache write at the scratch
                # row (max_seq-1, provably never attended: sequences
                # finish before reaching it) so they cannot corrupt a
                # half-prefilled prompt's rows
                lens = np.full(self.max_batch, self.max_seq - 1, np.int32)
                for slot, req in shard.active.items():
                    last[slot] = req.generated[-1]
                    temps[slot] = req.temperature
                    lens[slot] = shard.lengths[slot]
                toks, shard.cache, self._rng = self._decode(
                    self.params, shard.cache, last,
                    lens, temps, self._rng,
                )
                toks = np.asarray(toks)
                for slot in list(shard.active.keys()):
                    req = shard.active[slot]
                    # the decode consumed the previous token: account it
                    shard.lengths[slot] += 1
                    tok = int(toks[slot])
                    req.generated.append(tok)
                    out.append((req, tok))
                    total_len = shard.lengths[slot] + 1
                    if (
                        (req.eos_id is not None and tok == req.eos_id)
                        or len(req.generated) >= req.max_tokens
                        or total_len >= self.max_seq - 1
                    ):
                        self._finish(shard, slot)
            return out

    # ------------------------------------------------------------------
    def generate(self, prompt_ids: List[int], *, max_tokens: int = 64,
                 temperature: float = 0.0, eos_id: Optional[int] = None
                 ) -> List[int]:
        """Synchronous single-prompt convenience (batch path: step())."""
        req = GenRequest(
            request_id="sync", prompt_ids=list(prompt_ids),
            max_tokens=max_tokens, temperature=temperature, eos_id=eos_id,
        )
        ok = self.add_request(req)
        assert ok, "engine full"
        while not req.done:
            self.step()
        return req.generated
