"""ray_tpu.llm: LLM serving + batch inference on the in-tree Llama.

Parity: python/ray/llm/ (reference delegates the engine to vLLM and the
placement math to vllm_models.py:123-142; here both are native — the
XLA KV-cache engine in _internal/engine.py and TP x PP placement in
config.LLMConfig.placement_bundles)."""

from ._internal.engine import GenRequest, LlamaEngine
from .batch import build_llm_processor
from .config import LLMConfig, save_params_npz
from .lora import apply_lora, load_lora_adapter
from .serve import LLMServer, OpenAIServer, build_llm_app, build_openai_app

__all__ = [
    "GenRequest",
    "LLMConfig",
    "LLMServer",
    "LlamaEngine",
    "OpenAIServer",
    "apply_lora",
    "build_llm_app",
    "build_llm_processor",
    "build_openai_app",
    "load_lora_adapter",
    "save_params_npz",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("llm")
