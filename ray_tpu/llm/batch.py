"""Batch LLM inference over ray_tpu.data.

Parity: python/ray/llm/_internal/batch/ (vllm_engine_stage + Processor
configs) — a Dataset pipeline stage that runs prompts through a pool of
engine actors via ``map_batches(compute="actors")``, one engine per
actor, chips assigned through the normal TPU resource path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .config import LLMConfig


class _EngineUDF:
    """Callable-class UDF: builds the engine once per actor; each batch
    generates completions for the 'prompt_ids' column."""

    def __init__(self, llm_config: LLMConfig, max_tokens: int,
                 temperature: float):
        from ._internal.engine import LlamaEngine

        from ray_tpu.models import llama

        self.max_tokens = max_tokens
        self.temperature = temperature
        self.engine = LlamaEngine(
            llm_config.model_config or llama.LLAMA_TINY,
            llm_config.load_params(),
            max_batch=llm_config.max_batch_size,
            max_seq=llm_config.max_seq_len,
        )

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        from ._internal.engine import GenRequest

        prompts = [list(map(int, p)) for p in batch["prompt_ids"]]
        reqs = [
            GenRequest(
                request_id=str(i), prompt_ids=p,
                max_tokens=self.max_tokens, temperature=self.temperature,
            )
            for i, p in enumerate(prompts)
        ]
        # continuous batching across the whole micro-batch
        pending = list(reqs)
        while pending or self.engine.num_active():
            while pending and self.engine.has_capacity():
                self.engine.add_request(pending.pop(0))
            if self.engine.num_active():
                self.engine.step()
        import numpy as np

        maxlen = max(len(r.generated) for r in reqs)
        gen = np.full((len(reqs), maxlen), -1, dtype=np.int64)
        for i, r in enumerate(reqs):
            gen[i, : len(r.generated)] = r.generated
        return dict(
            batch,
            generated_ids=gen,
            num_generated=np.array([len(r.generated) for r in reqs]),
        )


def build_llm_processor(
    llm_config: LLMConfig,
    *,
    concurrency: int = 1,
    batch_size: int = 16,
    max_tokens: int = 32,
    temperature: float = 0.0,
):
    """Returns ds -> ds with a 'generated_ids' column (reference:
    build_llm_processor returning a Processor over vLLM stages)."""

    def apply(ds):
        num_tpus = (
            llm_config.tensor_parallel_size
            if llm_config.accelerator_type == "TPU"
            else 0
        )
        return ds.map_batches(
            _EngineUDF,
            fn_constructor_args=(llm_config, max_tokens, temperature),
            batch_size=batch_size,
            concurrency=concurrency,
            num_tpus=num_tpus or None,
        )

    return apply
