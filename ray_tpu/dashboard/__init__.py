"""Dashboard: the cluster's HTTP observability surface.

Parity: python/ray/dashboard/ (head.py:46 DashboardHead + modules) —
TPU-native scope: the operational API, not a React frontend. One aiohttp
server exposes the state API, metrics (Prometheus exposition), the
chrome-trace timeline, and job submission/inspection:

    GET  /api/cluster_status     nodes + aggregate resources
    GET  /api/nodes|actors|tasks|workers|objects|placement_groups
    GET  /api/shards             control-plane topology: per-reactor-
                                 shard conn/frame counters + state-
                                 service message counts (hub_shards.py)
    GET  /api/timeline           chrome://tracing JSON
    GET  /api/events             flight-recorder runtime events
    GET  /api/traces             sampled distributed traces (summaries)
    GET  /api/traces/{trace_id}  one trace: raw spans + critical-path
                                 breakdown (util/tracing.analyze_trace)
    GET  /api/serve              serve-plane SLOs: raw per-(deployment,
                                 route) metric rows + the per-deployment
                                 summary (latency percentiles, batch
                                 efficiency, drain/drop counters)
    GET  /api/profile            folded profiler samples + per-process
                                 sampler meta (empty unless
                                 RAY_TPU_PROFILE_HZ > 0 somewhere);
                                 ?fold=1 returns flamegraph collapsed
                                 text instead of JSON
    GET  /metrics                Prometheus text (user + ray_tpu_* builtin)
    GET  /api/jobs               scheduler view: {tenants (usage vs
                                 quota), jobs (fairsched registry),
                                 submissions (entrypoint job table)}
    GET  /api/tenants            per-tenant usage vs quota only
    POST /api/jobs               {"entrypoint": ..., "tenant": ...,
                                 "priority": ..., "quota": ...}
                                 -> {"job_id": ...}
    GET  /api/jobs/{id}          status
    GET  /api/jobs/{id}/logs     captured driver output
"""

from __future__ import annotations

import threading
from typing import Optional


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._thread: Optional[threading.Thread] = None
        self._loop = None
        self._started = threading.Event()

    # ------------------------------------------------------------------
    def start(self) -> "Dashboard":
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="ray-tpu-dashboard"
        )
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("dashboard failed to start within 10s")
        return self

    def _client(self):
        from ray_tpu._private import worker

        return worker.get_client()

    def _serve(self):
        import asyncio

        from aiohttp import web

        async def cluster_status(request):
            client = self._client()
            return web.json_response(
                {
                    "nodes": client.list_state("nodes"),
                    "resources_total": client.cluster_resources(False),
                    "resources_available": client.cluster_resources(True),
                }
            )

        async def list_kind(request):
            kind = request.match_info["kind"]
            allowed = {
                "nodes", "actors", "tasks", "workers", "objects",
                "placement_groups", "events", "tenants", "shards",
                "traces", "profile",
            }
            if kind not in allowed:
                raise web.HTTPNotFound(text=f"unknown kind {kind}")
            return web.json_response(self._client().list_state(kind))

        async def timeline(request):
            return web.json_response(self._client().list_state("timeline"))

        async def trace_detail(request):
            # one trace's raw spans + the critical-path breakdown
            from ray_tpu.util.tracing import analyze_trace

            spans = self._client().list_state(
                "traces", trace_id=request.match_info["trace_id"]
            )
            if not spans:
                raise web.HTTPNotFound(text="unknown or evicted trace")
            return web.json_response(
                {"spans": spans, "critical_path": analyze_trace(spans)}
            )

        async def data_stats(request):
            import json as _json

            client = self._client()
            out = []
            for key in sorted(client.kv_keys(b"__data_stats__"))[-20:]:
                blob = client.kv_get(key)
                if blob:
                    try:
                        out.append(_json.loads(blob))
                    except ValueError:
                        pass
            return web.json_response(out)

        async def metrics(request):
            from ray_tpu.util.metrics import prometheus_text

            return web.Response(text=prometheus_text(),
                                content_type="text/plain")

        async def profile_state(request):
            rows = self._client().list_state("profile")
            if request.query.get("fold"):
                from ray_tpu.util.profiler import fold_lines

                return web.Response(
                    text="\n".join(fold_lines(rows)) + "\n",
                    content_type="text/plain",
                )
            return web.json_response(rows)

        async def serve_state(request):
            from ray_tpu.util.state import summarize_serve

            return web.json_response({
                "rows": self._client().list_state("serve"),
                "summary": summarize_serve(),
            })

        def _jobs_client():
            from ray_tpu.job_submission import JobSubmissionClient

            return JobSubmissionClient()

        async def jobs_list(request):
            # the scheduler view (fairsched: per-tenant usage vs quota,
            # registered jobs) plus the entrypoint submission table.
            # Submissions are best-effort: reading them instantiates
            # the job-manager actor, which needs a live worker — the
            # scheduler tables must render even when that fails.
            client = self._client()
            try:
                submissions = _jobs_client().list_jobs()
            except Exception:
                submissions = []
            return web.json_response({
                "tenants": client.list_state("tenants"),
                "jobs": client.list_state("jobs"),
                "submissions": submissions,
            })

        async def jobs_submit(request):
            body = await request.json()
            job_id = _jobs_client().submit_job(
                entrypoint=body["entrypoint"],
                submission_id=body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"),
                tenant=body.get("tenant"),
                priority=body.get("priority"),
                quota=body.get("quota"),
            )
            return web.json_response({"job_id": job_id})

        async def job_status(request):
            return web.json_response(
                _jobs_client().get_job_info(request.match_info["job_id"])
            )

        async def job_logs(request):
            return web.Response(
                text=_jobs_client().get_job_logs(request.match_info["job_id"]),
                content_type="text/plain",
            )

        import os

        with open(os.path.join(os.path.dirname(__file__), "index.html")) as f:
            index_html = f.read()  # once: no per-request blocking read
                                   # on the event-loop thread

        async def index(request):
            return web.Response(text=index_html, content_type="text/html")

        app = web.Application()
        # literal routes BEFORE the /api/{kind} catch-all
        app.router.add_get("/", index)
        app.router.add_get("/api/cluster_status", cluster_status)
        app.router.add_get("/api/timeline", timeline)
        app.router.add_get("/api/data_stats", data_stats)
        app.router.add_get("/api/jobs", jobs_list)
        app.router.add_post("/api/jobs", jobs_submit)
        app.router.add_get("/api/jobs/{job_id}", job_status)
        app.router.add_get("/api/jobs/{job_id}/logs", job_logs)
        app.router.add_get("/api/traces/{trace_id}", trace_detail)
        app.router.add_get("/api/serve", serve_state)
        app.router.add_get("/api/profile", profile_state)
        app.router.add_get("/api/{kind}", list_kind)
        app.router.add_get("/metrics", metrics)

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        runner = web.AppRunner(app)
        self._loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        self._loop.run_until_complete(site.start())
        if self.port == 0:  # ephemeral bind: report the real port
            self.port = site._server.sockets[0].getsockname()[1]
        self._started.set()
        self._loop.run_forever()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    """Start (or return) the process-wide dashboard server."""
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard
