"""ray_tpu.tune — hyperparameter tuning.

Parity: python/ray/tune/ (Tuner :43,312, TuneController, searchers,
schedulers, sample space). tune.report/get_checkpoint are the Train
session functions (the reference unified them the same way).
"""

from ..train.session import get_checkpoint, report
from .sample import (
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from .schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    OptunaSearch,
    Searcher,
)
from .tuner import ResultGrid, TuneConfig, Tuner, with_resources

__all__ = [
    "ASHAScheduler",
    "BasicVariantGenerator",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "OptunaSearch",
    "PB2",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "lograndint",
    "loguniform",
    "quniform",
    "randint",
    "randn",
    "report",
    "sample_from",
    "uniform",
    "with_resources",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("tune")
