"""Trial schedulers: FIFO, ASHA, PBT.

Parity: python/ray/tune/schedulers/ (FIFOScheduler; ASHA
async_hyperband.py — asynchronous successive halving with rungs; PBT
pbt.py — exploit top quantile + explore by mutation).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    metric: Optional[str] = None
    mode: Optional[str] = None

    @property
    def _sign(self) -> float:
        return -1.0 if (self.mode or "min") == "min" else 1.0

    def set_metric_and_mode(self, metric: Optional[str], mode: Optional[str]) -> None:
        """Fill UNSET metric/mode from TuneConfig (controller calls this
        before launching trials); explicit scheduler args win."""
        if self.metric is None and metric:
            self.metric = metric
        if self.mode is None and mode:
            self.mode = mode

    def on_result(self, trial_id: str, metrics: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass

    # PBT hook: returns (source_trial_id, new_config) when the trial
    # should exploit another, else None
    def exploit(self, trial_id: str) -> Optional[tuple]:
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference:
    tune/schedulers/async_hyperband.py AsyncHyperBandScheduler).

    A trial reaching rung r (iteration = grace_period *
    reduction_factor^r) continues only if its metric is in the top
    1/reduction_factor of results recorded at that rung.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        time_attr: str = "training_iteration",
    ):
        # metric/mode may be deferred to TuneConfig (resolved by the
        # controller via set_metric_and_mode before the run starts)
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung iteration -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        # trial -> rung levels it has already been evaluated at
        self._recorded: Dict[str, set] = defaultdict(set)

    def _rung_levels(self) -> List[int]:
        levels = []
        t = self.grace
        while t < self.max_t:
            levels.append(t)
            t *= self.rf
        return levels

    def on_result(self, trial_id: str, metrics: Dict[str, Any]) -> str:
        t = metrics.get(self.time_attr, 0)
        val = metrics.get(self.metric) if self.metric else None
        if val is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # evaluate at every rung level CROSSED since the last report —
        # time_attr need not land exactly on grace * rf^r (a trial
        # reporting at t=1000, 2000, ... still hits rungs 1, 4, 16, ...)
        for level in self._rung_levels():
            if t >= level and level not in self._recorded[trial_id]:
                self._recorded[trial_id].add(level)
                rung = self._rungs[level]
                rung.append(self._sign * float(val))
                k = max(1, len(rung) // self.rf)
                cutoff = sorted(rung, reverse=True)[k - 1]
                if self._sign * float(val) < cutoff:
                    return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` iterations, bottom-quantile trials clone
    the state of a top-quantile trial (checkpoint exploit) and mutate
    its hyperparameters (explore)."""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        time_attr: str = "training_iteration",
        seed: Optional[int] = None,
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._last_perturb: Dict[str, int] = {}
        self._rng = random.Random(seed)

    def register_config(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def on_result(self, trial_id: str, metrics: Dict[str, Any]) -> str:
        self._latest[trial_id] = dict(metrics)
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        # drop the finished trial's state: it can no longer perturb and
        # must leave the exploit pool — and a long tuning run must not
        # accumulate one config/metrics dict per completed trial (GL009)
        self._configs.pop(trial_id, None)
        self._latest.pop(trial_id, None)
        self._last_perturb.pop(trial_id, None)

    def exploit(self, trial_id: str) -> Optional[tuple]:
        m = self._latest.get(trial_id)
        if not m or not self.metric or self.metric not in m:
            return None
        t = m.get(self.time_attr, 0)
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return None
        scores = {
            tid: self._sign * float(mm[self.metric])
            for tid, mm in self._latest.items()
            if self.metric in mm
        }
        if len(scores) < 2:
            return None
        ranked = sorted(scores, key=scores.get, reverse=True)
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        top, bottom = ranked[:k], ranked[n - k :]
        if trial_id not in bottom or trial_id in top:
            self._last_perturb[trial_id] = t
            return None
        source = self._rng.choice(top)
        new_config = self._mutate(self._configs.get(source, {}))
        # NOT committed yet: the controller confirms via commit_exploit
        # only after the restart-from-checkpoint actually happens, so a
        # skipped exploit (source has no checkpoint yet) leaves this
        # trial's population record truthful.
        return source, new_config

    def commit_exploit(self, trial_id: str, new_config: Dict[str, Any]) -> None:
        t = self._latest.get(trial_id, {}).get(self.time_attr, 0)
        self._last_perturb[trial_id] = t
        self._configs[trial_id] = dict(new_config)

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .sample import Domain

        out = dict(config)
        for k, spec in self.mutations.items():
            if isinstance(spec, Domain):
                out[k] = spec.sample(self._rng)
            elif isinstance(spec, list):
                out[k] = self._rng.choice(spec)
            elif callable(spec):
                out[k] = spec()
            elif k in out and isinstance(out[k], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[k] = type(out[k])(out[k] * factor)
        return out


class PB2(PopulationBasedTraining):
    """Population-based bandits (reference: tune/schedulers/pb2.py).

    PBT's exploit step with the random mutation replaced by a GP-UCB
    bandit: reward IMPROVEMENTS are modeled as a Gaussian process over
    (hyperparameters, time), and the explore step picks the
    highest-UCB point inside ``hyperparam_bounds`` — sample-efficient
    where PBT's multiplicative jitter is blind. Continuous bounds only
    (the paper's setting); categorical params pass through unchanged.
    GP backend: sklearn GaussianProcessRegressor (Matern 5/2).
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        perturbation_interval: int = 5,
        hyperparam_bounds: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        time_attr: str = "training_iteration",
        ucb_kappa: float = 1.0,
        seed: Optional[int] = None,
    ):
        super().__init__(
            metric=metric,
            mode=mode,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={},
            quantile_fraction=quantile_fraction,
            time_attr=time_attr,
            seed=seed,
        )
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds={name: (lo, hi)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        # observation history: per trial, last (t, config, score) to
        # turn absolute scores into per-interval improvements
        self._prev_obs: Dict[str, tuple] = {}
        self._X: List[List[float]] = []   # [normalized hp..., t]
        self._y: List[float] = []         # score improvement

    def on_result(self, trial_id: str, metrics: Dict[str, Any]) -> str:
        out = super().on_result(trial_id, metrics)
        if self.metric and self.metric in metrics:
            t = float(metrics.get(self.time_attr, 0))
            score = self._sign * float(metrics[self.metric])
            cfg = self._configs.get(trial_id, {})
            prev = self._prev_obs.get(trial_id)
            if prev is not None and all(k in cfg for k in self.bounds):
                pt, pscore = prev
                if t > pt:
                    self._X.append(self._featurize(cfg, pt))
                    self._y.append((score - pscore) / (t - pt))
            self._prev_obs[trial_id] = (t, score)
        return out

    def _featurize(self, config: Dict[str, Any], t: float) -> List[float]:
        feats = []
        for k, (lo, hi) in self.bounds.items():
            feats.append((float(config[k]) - lo) / max(hi - lo, 1e-12))
        feats.append(t)  # raw; normalized against max-t at fit time so
        # the isotropic kernel is not dominated by the time scale
        return feats

    def commit_exploit(self, trial_id: str, new_config: Dict[str, Any]) -> None:
        super().commit_exploit(trial_id, new_config)
        # the next report's score jump comes from the checkpoint CLONE,
        # not from the new hyperparameters — recording it would teach
        # the GP that whatever configs bottom trials clone into cause
        # huge improvements
        self._prev_obs.pop(trial_id, None)

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        if len(self._y) < 4:
            # cold start: uniform sample inside the bounds
            for k, (lo, hi) in self.bounds.items():
                out[k] = lo + self._rng.random() * (hi - lo)
            return out
        import numpy as np
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import Matern

        X = np.asarray(self._X, float)
        t_max = max(X[:, -1].max(), 1.0)
        X = X.copy()
        X[:, -1] /= t_max  # time on the same [0,1] scale as the hps
        y = np.asarray(self._y, float)
        y = (y - y.mean()) / (y.std() + 1e-9)
        gp = GaussianProcessRegressor(
            kernel=Matern(nu=2.5), alpha=1e-4, normalize_y=False,
            random_state=self._rng.randrange(2**31),
        )
        gp.fit(X, y)
        rng = np.random.default_rng(self._rng.randrange(2**31))
        n_cand = 256
        cand = rng.random((n_cand, len(self.bounds)))
        feats = np.concatenate(
            [cand, np.ones((n_cand, 1))], axis=1  # t = now = max = 1.0
        )
        mean, std = gp.predict(feats, return_std=True)
        best = int(np.argmax(mean + self.kappa * std))
        for i, (k, (lo, hi)) in enumerate(self.bounds.items()):
            out[k] = lo + float(cand[best, i]) * (hi - lo)
        return out
