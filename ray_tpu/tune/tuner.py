"""Tuner + TuneController: the experiment control loop.

Parity: python/ray/tune/tuner.py (Tuner.fit :312) driving
tune/execution/tune_controller.py:68 — an event loop that creates trial
actors, consumes their reported results, consults the scheduler
(stop/continue/exploit) and searcher (next configs), and assembles a
ResultGrid. Trials are TrainWorker actors (one-worker gangs) reusing
the Train session/report/checkpoint machinery — the same unification
the reference converged on (ray.tune.report == ray.train.report).
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ..air.config import CheckpointConfig, RunConfig
from ..air.result import Result
from ..train._checkpoint import Checkpoint
from ..train._internal.worker_group import TrainWorker
from .sample import Domain, GridSearch
from .schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from .search import BasicVariantGenerator, Searcher

_POLL_S = 0.05


@dataclass
class TuneConfig:
    """Parity: ray.tune.TuneConfig."""

    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    trial_resources: Optional[Dict[str, float]] = None
    seed: Optional[int] = None


class ResultGrid:
    """Parity: ray.tune.ResultGrid."""

    def __init__(self, results: List[Result], metric: Optional[str], mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric or pass one)")
        sign = 1.0 if mode == "max" else -1.0
        candidates = [
            r for r in self._results if r.metrics and metric in r.metrics
        ]
        if not candidates:
            raise RuntimeError("no trial reported the requested metric")
        return max(candidates, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])


@dataclass
class _Trial:
    trial_id: str
    config: Dict[str, Any]
    actor: Any = None
    status: str = "PENDING"  # PENDING RUNNING TERMINATED ERROR
    last_metrics: Optional[Dict[str, Any]] = None
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    storage_dir: str = ""
    iteration: int = 0


def with_resources(trainable: Callable, resources: Dict[str, float]) -> Callable:
    """Parity: tune.with_resources — attach per-trial resources."""
    trainable.__tune_resources__ = dict(resources)
    return trainable


class Tuner:
    def __init__(
        self,
        trainable: Union[Callable, Any],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_state: Optional[dict] = None

    # ------------------------------------------------------------------
    @classmethod
    def restore(
        cls,
        path: str,
        trainable: Union[Callable, Any],
        *,
        resume_unfinished: bool = True,
        restart_errored: bool = False,
    ) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference: Tuner.restore + tune/execution/experiment_state.py).
        Rehydrates the searcher/scheduler state and every trial's
        config/status/last-checkpoint; unfinished trials continue from
        their checkpoints, finished ones keep their results."""
        import cloudpickle

        state_path = os.path.join(path, "experiment_state.pkl")
        with open(state_path, "rb") as f:
            state = cloudpickle.load(f)
        tuner = cls(
            trainable,
            param_space=state["param_space"],
            tune_config=state["tune_config"],
            run_config=state["run_config"],
        )
        for t in state["trials"]:
            if t["status"] == "RUNNING" or (
                t["status"] == "PENDING" and resume_unfinished
            ):
                t["status"] = "PENDING"  # relaunch from checkpoint
            elif t["status"] == "ERROR" and restart_errored:
                t["status"] = "PENDING"
                t["error"] = None
        if not resume_unfinished:
            state["trials"] = [
                t for t in state["trials"] if t["status"] != "PENDING"
            ]
        tuner._restored_state = state
        return tuner

    @staticmethod
    def can_restore(path: str) -> bool:
        return os.path.exists(os.path.join(path, "experiment_state.pkl"))

    def _save_state(self, exp_dir, name, trials, counter, searcher, scheduler):
        """Atomic experiment snapshot after every trial-state change —
        the crash-consistency contract Tuner.restore relies on."""
        import cloudpickle

        state = {
            "name": name,
            "counter": counter,
            "param_space": self.param_space,
            "tune_config": self.tune_config,
            "run_config": self.run_config,
            "searcher": searcher,
            "scheduler": scheduler,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "status": t.status if t.status != "RUNNING" else "RUNNING",
                    "last_metrics": t.last_metrics,
                    "checkpoint_path": t.checkpoint_path,
                    "error": t.error,
                    "storage_dir": t.storage_dir,
                    "iteration": t.iteration,
                }
                for t in trials.values()
            ],
        }
        tmp = os.path.join(exp_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))

    # ------------------------------------------------------------------
    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)

        tc = self.tune_config
        restored = self._restored_state
        if restored is not None:
            name = restored["name"]
        else:
            name = self.run_config.name or f"tune_{uuid.uuid4().hex[:8]}"
        exp_dir = os.path.join(os.path.expanduser(self.run_config.storage_path), name)
        os.makedirs(exp_dir, exist_ok=True)

        if restored is not None:
            searcher = restored["searcher"]
            scheduler = restored["scheduler"]
        else:
            searcher = tc.search_alg or BasicVariantGenerator(
                self.param_space, num_samples=tc.num_samples, seed=tc.seed
            )
            scheduler = tc.scheduler or FIFOScheduler()
        scheduler.set_metric_and_mode(tc.metric, tc.mode)

        max_conc = tc.max_concurrent_trials or 4
        trials: Dict[str, _Trial] = {}
        counter = 0
        resume_queue: List[_Trial] = []
        if restored is not None:
            counter = restored["counter"]
            for t in restored["trials"]:
                trial = _Trial(
                    trial_id=t["trial_id"],
                    config=t["config"],
                    status=t["status"],
                    last_metrics=t["last_metrics"],
                    checkpoint_path=t["checkpoint_path"],
                    error=t["error"],
                    storage_dir=t["storage_dir"],
                    iteration=t["iteration"],
                )
                trials[trial.trial_id] = trial
                if trial.status == "PENDING":
                    resume_queue.append(trial)
        # Custom searchers (e.g. Optuna) can suggest unboundedly; cap
        # them at num_samples. BasicVariantGenerator self-limits (grid ×
        # num_samples) and reports exhaustion via is_finished().
        own_searcher = tc.search_alg is None
        trial_cap = None if own_searcher else max(tc.num_samples, 1)

        train_fn = self._as_train_fn()
        resources = dict(
            getattr(self.trainable, "__tune_resources__", None)
            or tc.trial_resources
            or {"CPU": 1}
        )

        def exhausted() -> bool:
            if searcher.is_finished():
                return True
            return trial_cap is not None and counter >= trial_cap

        dirty = True
        while True:
            # launch new trials up to the concurrency cap
            starved = False
            running = [t for t in trials.values() if t.status == "RUNNING"]
            # restored unfinished trials resume first (from checkpoint)
            while resume_queue and len(running) < max_conc:
                trial = resume_queue.pop(0)
                if hasattr(scheduler, "register_config"):
                    scheduler.register_config(trial.trial_id, trial.config)
                self._start_trial(trial, train_fn, resources)
                running.append(trial)
                dirty = True
            while not exhausted() and len(running) < max_conc:
                trial_id = f"{name}_{counter:05d}"
                cfg = searcher.suggest(trial_id)
                if cfg is None:
                    starved = True
                    break  # not now (concurrency-limited); retry next tick
                counter += 1
                trial = _Trial(trial_id, cfg, storage_dir=os.path.join(exp_dir, trial_id))
                if hasattr(scheduler, "register_config"):
                    scheduler.register_config(trial_id, cfg)
                self._start_trial(trial, train_fn, resources)
                trials[trial_id] = trial
                running.append(trial)
                dirty = True

            if not running:
                # nothing in flight and the searcher has nothing to give
                # right now — with no live trials to unblock it, that is
                # terminal (covers custom searchers with no is_finished)
                if exhausted() or starved:
                    break
                time.sleep(_POLL_S)
                continue

            # poll running trials
            import ray_tpu as ray

            for trial in list(running):
                try:
                    # sequential by design: per-trial error attribution
                    # needs each poll's exception on its own trial
                    poll = ray.get(trial.actor.poll.remote())  # graftlint: disable=GL004
                except Exception as e:
                    trial.status = "ERROR"
                    trial.error = str(e)
                    self._stop_actor(trial)
                    searcher.on_trial_complete(trial.trial_id, trial.last_metrics)
                    scheduler.on_trial_complete(trial.trial_id)
                    continue
                for row in poll["results"]:
                    metrics = dict(row["metrics"])
                    trial.iteration = row["iteration"] + 1
                    metrics.setdefault("training_iteration", trial.iteration)
                    metrics["trial_id"] = trial.trial_id
                    metrics["config"] = trial.config
                    trial.last_metrics = metrics
                    if row.get("checkpoint_path"):
                        trial.checkpoint_path = row["checkpoint_path"]
                        dirty = True
                    decision = scheduler.on_result(trial.trial_id, metrics)
                    if decision == STOP:
                        # rare control-path call, one trial at a time
                        ray.get(trial.actor.request_stop.remote())  # graftlint: disable=GL004
                # PBT exploit hook — only for trials still mid-training;
                # a finished/errored trial's poll flags belong to the OLD
                # actor and would immediately kill the exploit restart
                if not poll["finished"] and not poll["error"]:
                    exploit = scheduler.exploit(trial.trial_id)
                    if exploit is not None:
                        source_id, new_config = exploit
                        source = trials.get(source_id)
                        applied = self._exploit_trial(
                            trial, source, new_config, train_fn, resources
                        )
                        if applied and hasattr(scheduler, "commit_exploit"):
                            scheduler.commit_exploit(trial.trial_id, new_config)
                        if applied:
                            continue  # fresh actor; re-poll next tick
                if poll["error"]:
                    trial.status = "ERROR"
                    trial.error = poll["error"]
                    self._stop_actor(trial)
                    searcher.on_trial_complete(trial.trial_id, trial.last_metrics)
                    scheduler.on_trial_complete(trial.trial_id)
                    dirty = True
                elif poll["finished"]:
                    trial.status = "TERMINATED"
                    self._stop_actor(trial)
                    searcher.on_trial_complete(trial.trial_id, trial.last_metrics)
                    scheduler.on_trial_complete(trial.trial_id)
                    dirty = True
            if dirty:
                # crash-consistent snapshot for Tuner.restore
                self._save_state(exp_dir, name, trials, counter, searcher, scheduler)
                dirty = False
            time.sleep(_POLL_S)

        self._save_state(exp_dir, name, trials, counter, searcher, scheduler)
        results = [
            Result(
                metrics=t.last_metrics,
                checkpoint=Checkpoint(t.checkpoint_path) if t.checkpoint_path else None,
                error=RuntimeError(t.error) if t.error else None,
                path=t.storage_dir,
            )
            for t in trials.values()
        ]
        return ResultGrid(results, tc.metric, tc.mode)

    # ------------------------------------------------------------------
    def _as_train_fn(self) -> Callable:
        trainable = self.trainable
        if hasattr(trainable, "fit") and hasattr(trainable, "train_loop_per_worker"):
            # a Trainer instance: each trial runs trainer.fit() with the
            # trial config merged into train_loop_config (reference:
            # BaseTrainer wrapped as a Tune trainable, §3.4 step 1)
            def run_trainer(config):
                import copy
                import dataclasses

                from ..train.session import get_context, report as _report

                t = copy.copy(trainable)
                t.train_loop_config = {**(trainable.train_loop_config or {}), **config}
                # each trial gets its OWN storage namespace — trials
                # sharing the trainer's run name would overwrite each
                # other's checkpoint dirs
                trial_name = get_context().experiment_name
                rc = trainable.run_config
                t.run_config = dataclasses.replace(
                    rc, name=f"{rc.name or 'trainer'}_{trial_name}"
                )
                result = t.fit()
                if result.error:
                    raise result.error
                # surface the inner run's final metrics as THIS trial's
                # report so the controller/searcher see them
                if result.metrics:
                    _report(
                        {k: v for k, v in result.metrics.items() if k != "config"}
                    )
                return result.metrics

            return run_trainer
        return trainable

    def _start_trial(self, trial: _Trial, train_fn, resources) -> None:
        import ray_tpu

        worker_cls = ray_tpu.remote(TrainWorker)
        opts: Dict[str, Any] = {"num_cpus": resources.get("CPU", 1)}
        if resources.get("TPU"):
            opts["num_tpus"] = resources["TPU"]
        trial.actor = worker_cls.options(**opts).remote(1, trial.trial_id)
        os.makedirs(trial.storage_dir, exist_ok=True)
        ray_tpu.get(
            trial.actor.setup_session.remote(
                0,
                trial.storage_dir,
                trial.checkpoint_path,
                None,
                trial.iteration,
                True,  # sync_reports: step-synchronize with the controller
            )
        )
        ray_tpu.get(trial.actor.start_training.remote(train_fn, trial.config))
        trial.status = "RUNNING"

    def _stop_actor(self, trial: _Trial) -> None:
        import ray_tpu

        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def _exploit_trial(
        self, trial: _Trial, source: Optional[_Trial], new_config, train_fn, resources
    ) -> bool:
        """PBT exploit: restart `trial` from `source`'s checkpoint with
        mutated config (reference: pbt.py _exploit). Returns whether the
        exploit was applied (False when the source has no checkpoint
        yet — the scheduler's population record stays untouched)."""
        if source is None or source.checkpoint_path is None:
            return False
        self._stop_actor(trial)
        trial.config = dict(new_config)
        trial.checkpoint_path = source.checkpoint_path
        self._start_trial(trial, train_fn, resources)
        return True
