"""Search algorithms.

Parity: python/ray/tune/search/ — Searcher ABC, BasicVariantGenerator
(grid + random), ConcurrencyLimiter, and an optional OptunaSearch
adapter (gated on optuna being installed, like the reference's
soft-dependency searchers).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from .sample import resolve


class Searcher:
    """Suggest/observe interface (reference: tune/search/searcher.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def is_finished(self) -> bool:
        """True when suggest() will never yield another config. Default
        False: a None from suggest() means 'not now' (e.g. concurrency
        capped), and the controller bounds custom searchers by
        num_samples instead."""
        return False

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict[str, Any]] = None
    ) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion × num_samples random draws
    (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1, seed=None):
        super().__init__()
        self.param_space = param_space
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._queue: List[Dict[str, Any]] = []
        for _ in range(num_samples):
            self._queue.extend(resolve(param_space, self._rng))

    @property
    def total_trials(self) -> int:
        return len(self._queue)

    def is_finished(self) -> bool:
        return not self._queue

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._queue:
            return None
        return self._queue.pop(0)


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: tune/search/
    concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def is_finished(self) -> bool:
        return self.searcher.is_finished()

    def on_trial_complete(self, trial_id, result=None):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)


class OptunaSearch(Searcher):
    """Optuna TPE adapter (reference: tune/search/optuna/optuna_search.py).
    Soft dependency: raises ImportError with guidance if optuna is absent.
    """

    def __init__(self, param_space: Dict[str, Any], metric="loss", mode="min", seed=None):
        super().__init__(metric, mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the `optuna` package (not bundled); "
                "use BasicVariantGenerator or install optuna"
            ) from e
        self._optuna = optuna
        sampler = optuna.samplers.TPESampler(seed=seed)
        self._study = optuna.create_study(
            direction="minimize" if mode == "min" else "maximize", sampler=sampler
        )
        from .sample import Categorical, Domain, Float, Integer

        for k, v in param_space.items():
            if isinstance(v, Domain) and not isinstance(
                v, (Float, Integer, Categorical)
            ):
                raise ValueError(
                    f"OptunaSearch supports uniform/loguniform/randint/choice "
                    f"domains; param {k!r} is {type(v).__name__}"
                )
            if isinstance(v, dict):
                raise ValueError(
                    f"OptunaSearch does not support nested search spaces "
                    f"(param {k!r}); flatten the space"
                )
        self.param_space = param_space
        self._trials: Dict[str, Any] = {}

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        from .sample import Categorical, Float, Integer

        ot = self._study.ask()
        cfg = {}
        for k, v in self.param_space.items():
            if isinstance(v, Float):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, Integer):
                cfg[k] = ot.suggest_int(k, v.lower, v.upper - 1, log=v.log)
            elif isinstance(v, Categorical):
                cfg[k] = ot.suggest_categorical(k, v.categories)
            else:
                cfg[k] = v
        self._trials[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id, result=None):
        ot = self._trials.pop(trial_id, None)
        if ot is not None and result and self.metric in result:
            self._study.tell(ot, float(result[self.metric]))
