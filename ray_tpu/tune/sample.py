"""Search-space primitives.

Parity: python/ray/tune/search/sample.py (Domain/Categorical/Float/
Integer/grid_search) — the declarative param_space vocabulary.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False, q: float = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        import math

        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        import math

        if self.log:
            return int(
                round(
                    math.exp(rng.uniform(math.log(self.lower), math.log(self.upper - 1)))
                )
            )
        return rng.randint(self.lower, self.upper - 1)


class Function(Domain):
    """tune.sample_from: fn optionally receives the partially-resolved
    config (the reference passes the spec the same way)."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng, spec=None):
        try:
            argc = self.fn.__code__.co_argcount
        except AttributeError:
            argc = 1
        return self.fn(spec) if argc else self.fn()


class _Gauss(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class GridSearch:
    """Marker for exhaustive expansion (tune.grid_search parity)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def randn(mean: float = 0.0, sd: float = 1.0) -> Domain:
    return _Gauss(mean, sd)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def _collect_grids(space: Dict[str, Any], path: tuple) -> List[tuple]:
    """All (path, values) GridSearch entries at any nesting depth."""
    out: List[tuple] = []
    for k, v in space.items():
        if isinstance(v, GridSearch):
            out.append((path + (k,), v.values))
        elif isinstance(v, dict):
            out.extend(_collect_grids(v, path + (k,)))
    return out


def resolve(param_space: Dict[str, Any], rng: random.Random) -> List[Dict[str, Any]]:
    """Expand grid_search axes (cartesian product, nested dicts
    included) and sample Domains once per variant — the
    BasicVariantGenerator expansion (reference:
    tune/search/basic_variant.py). sample_from functions receive the
    config resolved so far (key order = insertion order)."""
    grids = _collect_grids(param_space, ())
    assignments: List[Dict[tuple, Any]] = [{}]
    for path, values in grids:
        assignments = [
            {**a, path: val} for a in assignments for val in values
        ]

    def build(space: Dict[str, Any], path: tuple, chosen: Dict[tuple, Any], cfg_root):
        cfg: Dict[str, Any] = {}
        for k, v in space.items():
            p = path + (k,)
            if isinstance(v, GridSearch):
                cfg[k] = chosen[p]
            elif isinstance(v, Function):
                cfg[k] = v.sample(rng, spec=cfg_root if path else cfg)
            elif isinstance(v, Domain):
                cfg[k] = v.sample(rng)
            elif isinstance(v, dict):
                cfg[k] = build(v, p, chosen, cfg_root or cfg)
            else:
                cfg[k] = v
        return cfg

    return [build(param_space, (), chosen, None) for chosen in assignments]
