"""JobConfig: a driver/job's multi-tenant scheduling identity.

Parity target: ``ray.job_config.JobConfig`` (python/ray/job_config.py)
— extended with the fairsched fields this runtime's multi-tenant
scheduler consumes (ray_tpu/_private/fairsched.py):

- ``tenant``: the accounting/fairness principal. All jobs of one tenant
  share its quota and its fair-share clock.
- ``priority``: integer, higher wins. Orders dispatch ahead of lower
  priorities, and lets this job's placement-group / SLICE reservations
  preempt strictly-lower-priority gangs when they cannot fit.
- ``quota``: optional resource caps (hub units: whole TPU chips, CPU
  cores, "memory" bytes). Tasks that would push the tenant's admitted
  usage over quota park as ``pending_quota`` instead of dispatching.

Pass to ``ray_tpu.init(job_config=...)``; submitted jobs
(``ray_tpu job submit --tenant ... --priority ...``) inherit theirs
through ``RAY_TPU_JOB_*`` environment variables, which ``init()`` reads
when no explicit config is given.
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Dict, Optional

_ENV_TENANT = "RAY_TPU_JOB_TENANT"
_ENV_PRIORITY = "RAY_TPU_JOB_PRIORITY"
_ENV_QUOTA = "RAY_TPU_JOB_QUOTA"  # JSON dict, e.g. '{"TPU": 4}'
_ENV_JOB_ID = "RAY_TPU_JOB_ID"


class JobConfig:
    def __init__(
        self,
        tenant: str = "default",
        priority: int = 0,
        quota: Optional[Dict[str, float]] = None,
        job_id: Optional[str] = None,
    ):
        self.tenant = tenant or "default"
        self.priority = int(priority or 0)
        # tri-state: None = no opinion (an existing tenant cap stands);
        # a dict — including {} — is declared and replaces the tenant's
        # cap (quota={} lifts an earlier one)
        self.quota = (
            None if quota is None
            else {k: float(v) for k, v in quota.items()}
        )
        if self.quota and any(v < 0 for v in self.quota.values()):
            raise ValueError(f"quota amounts must be >= 0, got {quota}")
        self.job_id = job_id or f"job-{uuid.uuid4().hex[:12]}"

    @classmethod
    def from_env(cls) -> Optional["JobConfig"]:
        """Build from RAY_TPU_JOB_* env vars (set by `job submit`), or
        None when no identity was handed down."""
        if not (
            os.environ.get(_ENV_TENANT)
            or os.environ.get(_ENV_PRIORITY)
            or os.environ.get(_ENV_QUOTA)
            or os.environ.get(_ENV_JOB_ID)
        ):
            return None
        quota: Optional[Dict[str, float]] = None
        raw = os.environ.get(_ENV_QUOTA)
        if raw is not None:
            try:
                quota = {
                    str(k): float(v) for k, v in json.loads(raw).items()
                }
            except (ValueError, TypeError, AttributeError):
                import sys

                sys.stderr.write(
                    f"[ray_tpu] ignoring malformed {_ENV_QUOTA}={raw!r} "
                    "(expected a JSON object of resource: amount)\n"
                )
        try:
            priority = int(os.environ.get(_ENV_PRIORITY) or 0)
        except ValueError:
            priority = 0
        return cls(
            tenant=os.environ.get(_ENV_TENANT) or "default",
            priority=priority,
            quota=quota,
            job_id=os.environ.get(_ENV_JOB_ID) or None,
        )

    def env_vars(self) -> Dict[str, str]:
        """The env handoff `job submit` gives its entrypoint so the
        job's own init() registers under this identity."""
        out = {_ENV_TENANT: self.tenant, _ENV_PRIORITY: str(self.priority),
               _ENV_JOB_ID: self.job_id}
        if self.quota is not None:
            out[_ENV_QUOTA] = json.dumps(self.quota)
        return out

    def __repr__(self) -> str:
        return (
            f"JobConfig(tenant={self.tenant!r}, priority={self.priority}, "
            f"quota={self.quota}, job_id={self.job_id!r})"
        )
