"""DAG authoring: lazy task/actor-method graphs.

Parity: python/ray/dag/ (dag_node.py, input_node.py, function_node.py,
class_node.py) — `fn.bind(...)` / `actor.method.bind(...)` build a lazy
DAG; `dag.execute(input)` runs it. The compiled path
(compiled_dag.py) pre-plans the schedule the way the reference's
CompiledDAG does (compiled_dag_node.py:805).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_counter = itertools.count()


class DAGNode:
    def __init__(self, args: Tuple = (), kwargs: Optional[Dict] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}
        self._id = next(_node_counter)

    # -- traversal -----------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            scan(a)
        return out

    def _topo(self) -> List["DAGNode"]:
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if node._id in seen:
                return
            seen[node._id] = node
            for c in node._children():
                visit(c)
            order.append(node)

        visit(self)
        return order

    # -- execution -----------------------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Eager execution: submit every node's task in topo order,
        passing upstream ObjectRefs directly (worker-to-worker data
        flow; the driver only holds refs)."""
        results: Dict[int, Any] = {}
        for node in self._topo():
            results[node._id] = node._apply(results, input_args, input_kwargs)
        return results[self._id]

    def _resolve_args(self, results, input_args, input_kwargs):
        def res(v):
            if isinstance(v, DAGNode):
                return results[v._id]
            if isinstance(v, list):
                return [res(x) for x in v]
            if isinstance(v, tuple):
                return tuple(res(x) for x in v)
            if isinstance(v, dict):
                return {k: res(x) for k, x in v.items()}
            return v

        args = tuple(res(a) for a in self._bound_args)
        kwargs = {k: res(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _apply(self, results, input_args, input_kwargs):
        raise NotImplementedError

    def with_shm_channel(self, shape, dtype: str = "float32") -> "DAGNode":
        """Declare this node's output as a fixed-shape numpy payload so
        experimental_compile() can pre-allocate a shared-memory ring
        channel for it (reference: with_type_hint/TorchTensorType on DAG
        nodes feeding the channel allocator)."""
        self._channel_spec = (tuple(shape), dtype)
        return self

    def experimental_compile(self, **kwargs):
        from .compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Placeholder for execute()'s argument (reference: dag/input_node.py).
    Context-manager form mirrors the reference's `with InputNode() as inp`.
    """

    def __init__(self):
        super().__init__()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _apply(self, results, input_args, input_kwargs):
        if len(input_args) == 1 and not input_kwargs:
            return input_args[0]
        if not input_args and not input_kwargs:
            return None
        return (input_args, input_kwargs)

    def __getattr__(self, key: str):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key):
        return InputAttributeNode(self, key)


class InputAttributeNode(DAGNode):
    """inp.x / inp[0] — projects a field out of the input."""

    def __init__(self, parent: InputNode, key):
        super().__init__(args=(parent,))
        self._key = key

    def _apply(self, results, input_args, input_kwargs):
        base = results[self._bound_args[0]._id]
        if isinstance(self._key, str):
            if isinstance(base, dict):
                return base[self._key]
            return getattr(base, self._key)
        return base[self._key]


class FunctionNode(DAGNode):
    """fn.bind(...) (reference: dag/function_node.py)."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _apply(self, results, input_args, input_kwargs):
        args, kwargs = self._resolve_args(results, input_args, input_kwargs)
        return self._remote_fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    """actor.method.bind(...) (reference: dag/class_node.py)."""

    def __init__(self, actor_method, args, kwargs):
        super().__init__(args, kwargs)
        self._method = actor_method

    def _apply(self, results, input_args, input_kwargs):
        args, kwargs = self._resolve_args(results, input_args, input_kwargs)
        return self._method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one output list (reference:
    dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(args=tuple(outputs))

    def _apply(self, results, input_args, input_kwargs):
        return [results[n._id] for n in self._bound_args]


class CollectiveOutputNode(DAGNode):
    """One participant's reduced output of an in-DAG allreduce
    (reference: dag/collective_node.py CollectiveOutputNode over the
    Communicator ABC, experimental/channel/communicator.py:19).

    Channel-compiled execution runs the reduction INSIDE the resident
    exec loops: the group's actors exchange contributions over a full
    mesh of pre-allocated shm ring channels and reduce locally — zero
    scheduler round-trips per tick. (The TPU-side analogue of the
    reference's NCCL allreduce node is a jitted psum over the mesh —
    ray_tpu.parallel — this node is the host/DAG-plane counterpart.)
    """

    def __init__(self, parent: "ClassMethodNode", group: List["ClassMethodNode"],
                 rank: int, op: str):
        # every group member is a real dependency: the reduce needs all
        # contributions, and the topo schedule must order them first
        super().__init__(args=tuple(group))
        self._parent = parent
        self._group = group
        self._rank = rank
        self._op = op
        self._channel_spec = getattr(parent, "_channel_spec", None)

    def _apply(self, results, input_args, input_kwargs):
        # legacy (non-channel) mode: resolve every participant's ref and
        # reduce driver-side — semantics preserved without loops
        import numpy as np

        import ray_tpu

        vals = [
            np.asarray(v)
            for v in ray_tpu.get([results[n._id] for n in self._group])
        ]
        acc = vals[0].copy()
        for v in vals[1:]:
            acc = _REDUCE_OPS[self._op](acc, v)
        return ray_tpu.put(acc)


_REDUCE_OPS = {
    "sum": lambda a, b: a + b,
    "prod": lambda a, b: a * b,
    "max": lambda a, b: __import__("numpy").maximum(a, b),
    "min": lambda a, b: __import__("numpy").minimum(a, b),
}


class _AllReduce:
    """`allreduce.bind([n1, n2, ...])` — binds an allreduce across DAG
    nodes living on distinct actors, returning one CollectiveOutputNode
    per input (reference: ray.experimental.collective.allreduce)."""

    @staticmethod
    def bind(nodes: List["ClassMethodNode"], op: str = "sum"
             ) -> List["CollectiveOutputNode"]:
        if op not in _REDUCE_OPS:
            raise ValueError(f"unsupported allreduce op {op!r}")
        if len(nodes) < 2:
            raise ValueError("allreduce needs at least two participants")
        for n in nodes:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "allreduce participants must be actor-method nodes"
                )
        actors = {n._method._handle._actor_id.binary() for n in nodes}
        if len(actors) != len(nodes):
            raise ValueError("allreduce participants must be distinct actors")
        return [
            CollectiveOutputNode(n, list(nodes), i, op)
            for i, n in enumerate(nodes)
        ]


allreduce = _AllReduce()
