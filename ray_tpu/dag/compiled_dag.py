"""Compiled DAG execution.

Parity: python/ray/dag/compiled_dag_node.py:805 (CompiledDAG). Two
modes:

**Channel mode** (the reference's true compiled path): when every
compute node is an actor-method node annotated with a fixed-shape
channel (``node.with_shm_channel(shape, dtype)``), compilation

1. allocates one shared-memory ring channel per DAG edge
   (experimental/channel/shm_channel.py — the analogue of the
   reference's mutable-plasma channels,
   shared_memory_channel.py:151), and
2. parks a resident exec loop on each actor via ``__ray_call__``
   (the reference's ``do_exec_tasks`` :193).

``execute()`` is then pure channel I/O — the driver writes the input
segment and later reads the output segment; the scheduler sees ZERO
task submissions per execution (asserted in tests via the hub's task
counters). In-flight executions pipeline up to the ring capacity.

**Legacy mode** (fallback for un-annotated graphs): the frozen topo
schedule re-submits tasks per execute with refs flowing
worker-to-worker — still no per-execute graph traversal, but each node
costs a scheduler round trip.

The TPU mapping of the reference's NCCL channels — HBM buffers between
jitted stages over ICI — is jit-level: ray_tpu.parallel.pipeline moves
stage activations with `lax.ppermute` inside ONE program.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .dag_node import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)


def _compiled_exec_loop(instance, method_name, arg_plan, out_descs, stop_desc,
                        coll_plan=None):
    """Resident per-actor loop (reference: do_exec_tasks,
    compiled_dag_node.py:193). Runs inside the actor via __ray_call__:
    read inputs from ring channels, run the method, write outputs —
    until the stop channel signals teardown.

    coll_plan (reference: dag/collective_node.py executing over the
    Communicator ABC) runs an allreduce INSIDE the loop: the method
    output is exchanged with the group's peers over a full mesh of shm
    channels and reduced locally, then the reduced array flows to
    coll_plan["outs"]. Zero scheduler traffic per tick."""
    import numpy as np

    from ray_tpu.experimental.channel.shm_channel import ShmChannel

    chans = {}

    def attach(desc):
        if desc not in chans:
            chans[desc] = ShmChannel(*desc)
        return chans[desc]

    ins = [
        ("chan", attach(kind_val)) if kind == "chan" else ("lit", kind_val)
        for kind, kind_val in arg_plan
    ]
    outs = [attach(d) for d in out_descs]
    stop = attach(stop_desc)
    if coll_plan is not None:
        from ray_tpu.dag.dag_node import _REDUCE_OPS

        coll_sends = [attach(d) for d in coll_plan["sends"]]
        coll_recvs = [attach(d) for d in coll_plan["recvs"]]
        coll_outs = [attach(d) for d in coll_plan["outs"]]
        coll_reduce = _REDUCE_OPS[coll_plan["op"]]
    method = getattr(instance, method_name)
    try:
        while True:
            args = []
            stopped = False
            for kind, src in ins:
                if kind == "lit":
                    args.append(src)
                    continue
                while True:
                    if stop.try_read() is not None:
                        stopped = True
                        break
                    try:
                        args.append(src.read(timeout_s=0.2))
                        break
                    except TimeoutError:
                        continue
                if stopped:
                    break
            if stopped:
                return "stopped"
            if not ins and stop.try_read() is not None:
                return "stopped"
            out = method(*args)
            for ch in outs:
                ch.write(np.asarray(out))
            if coll_plan is not None:
                contrib = np.asarray(out)
                # all ranks send first (ring capacity absorbs skew) ...
                for ch in coll_sends:
                    ch.write(contrib)
                # ... then fold in GLOBAL rank order so every rank
                # computes bit-identical floats (recvs arrive ordered by
                # peer rank; own contribution slots in at coll_plan rank)
                contribs = []
                stopped = False
                for slot, ch in enumerate(coll_recvs):
                    if slot == coll_plan["rank"]:
                        contribs.append(contrib)
                    while True:
                        if stop.try_read() is not None:
                            stopped = True
                            break
                        try:
                            contribs.append(ch.read(timeout_s=0.2))
                            break
                        except TimeoutError:
                            continue
                    if stopped:
                        return "stopped"
                if len(contribs) == len(coll_recvs):
                    contribs.append(contrib)  # own rank is last
                acc = contribs[0].copy()
                for c in contribs[1:]:
                    acc = coll_reduce(acc, c)
                for ch in coll_outs:
                    ch.write(acc)
    finally:
        for ch in chans.values():
            ch.close()


class CompiledDAGRef:
    """Future for one compiled execution (reference:
    experimental/compiled_dag_ref.py). Channel mode delivers results in
    execution order — get() must follow that order."""

    def __init__(self, dag: "CompiledDAG", value=None, seq: Optional[int] = None):
        self._dag = dag
        self._value = value
        self._seq = seq
        self._result = None
        self._got = False

    def get(self, timeout: Optional[float] = None):
        if self._seq is not None:  # channel mode
            return self._dag._channel_get(self, timeout)
        import ray_tpu

        self._dag._retire(self)
        return ray_tpu.get(self._value, timeout=timeout)

    def _wait_done(self) -> None:
        """Completion only — no payload fetch (backpressure path)."""
        import ray_tpu

        refs = self._value if isinstance(self._value, list) else [self._value]
        ray_tpu.wait(refs, num_returns=len(refs))


class CompiledDAG:
    def __init__(self, root: DAGNode, *, max_inflight_executions: int = 10):
        self._root = root
        self._schedule = root._topo()  # frozen order
        self._max_inflight = max_inflight_executions
        self._inflight: deque = deque()
        self._inputs = [n for n in self._schedule if type(n) is InputNode]
        if len(self._inputs) > 1:
            raise ValueError("compiled DAG must have exactly one InputNode")
        self._channel_mode = False
        self._torn_down = False
        if self._qualifies_for_channels():
            self._compile_channels()

    # ------------------------------------------------------- channel mode
    def _qualifies_for_channels(self) -> bool:
        for node in self._schedule:
            if type(node) in (InputNode, MultiOutputNode):
                continue
            if isinstance(node, ClassMethodNode) and getattr(
                node, "_channel_spec", None
            ):
                continue
            if isinstance(node, CollectiveOutputNode) and node._channel_spec:
                continue
            return False
        leaves = (
            list(self._root._bound_args)
            if isinstance(self._root, MultiOutputNode)
            else [self._root]
        )
        return bool(self._inputs) and all(
            isinstance(x, (ClassMethodNode, CollectiveOutputNode))
            for x in leaves
        )

    def _compile_channels(self) -> None:
        import ray_tpu
        from ray_tpu.experimental.channel.shm_channel import ShmChannel

        cap = self._max_inflight
        # one SPSC channel per edge (producer node -> consumer node);
        # the driver is producer for input edges and consumer of leaves
        self._edge_chans: Dict[Tuple[int, int], ShmChannel] = {}

        def edge(producer: DAGNode, consumer_id: int, spec) -> ShmChannel:
            key = (producer._id, consumer_id)
            if key not in self._edge_chans:
                self._edge_chans[key] = ShmChannel.create(
                    shape=spec[0], dtype=spec[1], capacity=cap
                )
            return self._edge_chans[key]

        def desc(ch: ShmChannel):
            # backend travels in the descriptor: both endpoints must map
            # the same segment layout (native C++ ring vs numpy ring)
            return (ch.name, ch.shape, str(ch.dtype), ch.capacity, False,
                    ch.backend)

        self._stop_chans: List[ShmChannel] = []
        self._loop_refs = []
        actors_seen = set()
        compute_nodes = [
            n for n in self._schedule if isinstance(n, ClassMethodNode)
        ]
        # collective groups: a full mesh of peer channels per group
        # (reference: collective_node.py binds a Communicator; here the
        # "communicator" is the pre-allocated channel mesh). Keyed by
        # parent node id -> per-actor exchange plan.
        coll_nodes = [
            n for n in self._schedule if isinstance(n, CollectiveOutputNode)
        ]
        self._coll_plans: Dict[int, dict] = {}
        groups_done = set()
        for cnode in coll_nodes:
            gkey = (cnode._op, tuple(sorted(p._id for p in cnode._group)))
            if gkey in groups_done:
                continue
            groups_done.add(gkey)
            group = cnode._group
            spec = cnode._channel_spec
            for parent in group:
                if parent._id in self._coll_plans:
                    # one exec loop per actor runs ONE exchange per
                    # tick; a parent in two groups (different op or
                    # overlapping membership) would need two
                    raise ValueError(
                        "channel-compiled DAGs support one collective "
                        "per participating node (node "
                        f"{parent._method._name!r} is in two groups)"
                    )
            # mesh channels live in their own key namespace — a data
            # edge between two group members (one parent feeding
            # another) must NOT share a channel with the exchange
            def mesh(src, dst):
                key = ("mesh", src._id, dst._id)
                if key not in self._edge_chans:
                    self._edge_chans[key] = ShmChannel.create(
                        shape=spec[0], dtype=spec[1], capacity=cap
                    )
                return self._edge_chans[key]

            for i, src in enumerate(group):
                for j, dst in enumerate(group):
                    if i != j:
                        mesh(src, dst)
            for i, parent in enumerate(group):
                self._coll_plans[parent._id] = {
                    "op": cnode._op,
                    "rank": i,
                    "sends": [
                        desc(mesh(parent, dst))
                        for j, dst in enumerate(group) if j != i
                    ],
                    # recvs ordered by peer rank for the deterministic
                    # global fold in the exec loop
                    "recvs": [
                        desc(mesh(src, parent))
                        for j, src in enumerate(group) if j != i
                    ],
                    "outs": [],  # filled by the out-edge pass below
                }
        for node in compute_nodes:
            actor = node._method._handle
            aid = actor._actor_id.binary()
            if aid in actors_seen:
                raise ValueError(
                    "channel-compiled DAGs support one node per actor "
                    "(the resident exec loop pins the actor)"
                )
            actors_seen.add(aid)
            arg_plan = []
            for arg in node._bound_args:
                if isinstance(arg, InputNode):
                    spec = getattr(arg, "_channel_spec", None) or node._channel_spec
                    arg_plan.append(("chan", desc(edge(arg, node._id, spec))))
                elif isinstance(arg, ClassMethodNode):
                    arg_plan.append(
                        ("chan", desc(edge(arg, node._id, arg._channel_spec)))
                    )
                elif isinstance(arg, DAGNode):
                    raise ValueError(
                        f"unsupported upstream node {type(arg).__name__} in "
                        "channel-compiled DAG"
                    )
                else:
                    arg_plan.append(("lit", arg))
            # output edges materialize when consumers register; collect
            # them after the full pass
            node._arg_plan = arg_plan
        # second pass: each node's out-edges (to consumers or the driver)
        self._out_chans: List[ShmChannel] = []
        leaves = (
            list(self._root._bound_args)
            if isinstance(self._root, MultiOutputNode)
            else [self._root]
        )
        for cnode in coll_nodes:
            if cnode in leaves:
                ch = edge(cnode, -1, cnode._channel_spec)  # -1 = driver
                self._coll_plans[cnode._parent._id]["outs"].append(desc(ch))
        for node in compute_nodes:
            out_descs = []
            for key, ch in self._edge_chans.items():
                # mesh keys are ("mesh", src, dst) — never match a node id
                if key[0] == node._id:
                    out_descs.append(desc(ch))
            if node in leaves:
                ch = edge(node, -1, node._channel_spec)  # -1 = driver
                out_descs.append(desc(ch))
            stop = ShmChannel.create(shape=(1,), dtype="int8", capacity=4)
            self._stop_chans.append(stop)
            self._loop_refs.append(
                node._method._handle.__ray_call__.remote(
                    _compiled_exec_loop,
                    node._method._name,
                    node._arg_plan,
                    out_descs,
                    desc(stop),
                    self._coll_plans.get(node._id),
                )
            )
        self._driver_out = [self._edge_chans[(leaf._id, -1)] for leaf in leaves]
        self._multi_output = isinstance(self._root, MultiOutputNode)
        self._input_edges = [
            ch for key, ch in self._edge_chans.items()
            if key[0] == self._inputs[0]._id
        ] if self._inputs else []
        self._seq_submit = itertools.count()
        self._seq_read = 0
        self._channel_mode = True

    def _channel_execute(self, args) -> CompiledDAGRef:
        import numpy as np

        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if len(args) != 1:
            raise ValueError("channel-compiled DAGs take exactly one input array")
        while len(self._inflight) >= self._max_inflight:
            # backpressure: block until the oldest result is consumed
            oldest = self._inflight[0]
            self._channel_get(oldest, timeout=60.0)
        arr = np.asarray(args[0])
        for ch in self._input_edges:
            ch.write(arr)
        ref = CompiledDAGRef(self, seq=next(self._seq_submit))
        self._inflight.append(ref)
        return ref

    def _channel_get(self, ref: CompiledDAGRef, timeout: Optional[float]):
        if ref._got:
            return ref._result
        if ref._seq != self._seq_read:
            raise RuntimeError(
                "channel-mode results must be consumed in execution order "
                f"(next is seq {self._seq_read}, asked for {ref._seq})"
            )
        out = [ch.read(timeout_s=timeout or 60.0) for ch in self._driver_out]
        ref._result = out if self._multi_output else out[0]
        ref._got = True
        self._seq_read += 1
        try:
            self._inflight.remove(ref)
        except ValueError:
            pass
        return ref._result

    # ---------------------------------------------------------- execution
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._channel_mode:
            return self._channel_execute(args)
        while len(self._inflight) >= self._max_inflight:
            oldest = self._inflight.popleft()
            oldest._wait_done()
        results: Dict[int, Any] = {}
        for node in self._schedule:
            results[node._id] = node._apply(results, args, kwargs)
        ref = CompiledDAGRef(self, value=results[self._root._id])
        self._inflight.append(ref)
        return ref

    def _retire(self, ref: CompiledDAGRef) -> None:
        try:
            self._inflight.remove(ref)
        except ValueError:
            pass

    def teardown(self) -> None:
        self._inflight.clear()
        if self._channel_mode and not self._torn_down:
            self._torn_down = True
            import numpy as np

            import ray_tpu

            for stop in self._stop_chans:
                try:
                    stop.write(np.zeros(1, dtype=np.int8))
                except TimeoutError:
                    pass
            try:
                ray_tpu.get(self._loop_refs, timeout=10)
            except Exception:
                pass
            for ch in self._edge_chans.values():
                ch.close(unlink=True)
            for stop in self._stop_chans:
                stop.close(unlink=True)

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
