"""Compiled DAG execution.

Parity: python/ray/dag/compiled_dag_node.py:805 (CompiledDAG) — the
reference compiles an actor-task DAG into a static pipeline: per-actor
resident exec loops plus pre-allocated channels, so each execute() is
channel writes, not task submissions. On this runtime the compile step:

1. freezes the topological schedule (no per-execute graph traversal),
2. pre-resolves each node's (callable, upstream-slot) plan,
3. submits the WHOLE graph's tasks back-to-back per execute, with
   upstream ObjectRefs passed directly (data flows worker→worker
   through the shared-memory object plane; the driver never touches
   payloads), and
4. supports overlapped executions in flight (the pipelining
   compiled graphs exist for) bounded by ``max_inflight_executions``.

The TPU mapping of the reference's NCCL channels — mutable HBM
buffers between jitted stages — lives in
ray_tpu.experimental.channel (host shm ring channels today; the ICI
path is jit-level, see ray_tpu.parallel.pipeline which moves
stage→stage activations with `lax.ppermute` inside ONE program).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from .dag_node import DAGNode, InputAttributeNode, InputNode, MultiOutputNode


class CompiledDAGRef:
    """Future for one compiled execution (reference:
    experimental/compiled_dag_ref.py)."""

    def __init__(self, dag: "CompiledDAG", value):
        self._dag = dag
        self._value = value

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        self._dag._retire(self)
        return ray_tpu.get(self._value, timeout=timeout)

    def _wait_done(self) -> None:
        """Completion only — no payload fetch (backpressure path)."""
        import ray_tpu

        refs = self._value if isinstance(self._value, list) else [self._value]
        ray_tpu.wait(refs, num_returns=len(refs))


class CompiledDAG:
    def __init__(self, root: DAGNode, *, max_inflight_executions: int = 10):
        self._root = root
        self._schedule = root._topo()  # frozen order
        self._max_inflight = max_inflight_executions
        self._inflight: deque = deque()
        # sanity: compiled graphs take exactly one InputNode
        self._inputs = [n for n in self._schedule if type(n) is InputNode]
        if len(self._inputs) > 1:
            raise ValueError("compiled DAG must have exactly one InputNode")

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        while len(self._inflight) >= self._max_inflight:
            # backpressure: wait for the oldest execution to COMPLETE —
            # no result fetch; payloads stay in the object plane
            oldest = self._inflight.popleft()
            oldest._wait_done()
        results: Dict[int, Any] = {}
        for node in self._schedule:
            results[node._id] = node._apply(results, args, kwargs)
        ref = CompiledDAGRef(self, results[self._root._id])
        self._inflight.append(ref)
        return ref

    def _retire(self, ref: CompiledDAGRef) -> None:
        try:
            self._inflight.remove(ref)
        except ValueError:
            pass

    def teardown(self) -> None:
        self._inflight.clear()
