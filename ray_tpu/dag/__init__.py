"""ray_tpu.dag — lazy DAG authoring + compiled execution.

Parity: python/ray/dag/ (InputNode/MultiOutputNode/bind;
CompiledDAG via dag.experimental_compile()).
"""

from .compiled_dag import CompiledDAG, CompiledDAGRef
from .dag_node import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
    allreduce,
)

__all__ = [
    "ClassMethodNode",
    "CollectiveOutputNode",
    "allreduce",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "FunctionNode",
    "InputAttributeNode",
    "InputNode",
    "MultiOutputNode",
]
