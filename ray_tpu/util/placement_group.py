"""Placement groups: atomic multi-bundle resource reservations.

Parity: python/ray/util/placement_group.py (:41 PlacementGroup, :145
placement_group()). Strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
from the reference, plus the TPU-native "SLICE" strategy: bundles are
mapped onto ICI-contiguous chips of one slice so a gang-scheduled
jax.distributed group gets a torus-contiguous sub-mesh (the reference
approximates this with per-pod custom resources, python/ray/_private/
accelerators/tpu.py:375; here it is a first-class strategy).

On the single-host runtime every strategy degenerates to reserving
bundles against the node; the 2-phase prepare/commit of the reference's
GcsPlacementGroupScheduler (gcs_placement_group_scheduler.h:122) is not
needed until multi-node lands.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .._private.ids import PlacementGroupID
from ..object_ref import ObjectRef

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "SLICE")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self._bundles = bundles
        self._strategy = strategy

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self) -> ObjectRef:
        """An ObjectRef that resolves (to True) when all bundles are reserved."""
        from .._private import worker

        client = worker.get_client()
        from .._private.ids import ObjectID

        oid = ObjectID.generate()

        def waiter():
            ok = client.pg_ready(self.id.binary(), timeout=3600.0)
            client.put_value(ok, object_id=oid)

        threading.Thread(target=waiter, daemon=True).start()
        return ObjectRef(oid)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        from .._private import worker

        return worker.get_client().pg_ready(self.id.binary(), timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles, self._strategy))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    priority: Optional[int] = None,
    tenant: Optional[str] = None,
) -> PlacementGroup:
    """``priority``/``tenant`` override the driver's registered
    JobConfig identity for this reservation (fairsched): a
    higher-priority reservation that cannot fit may preempt
    strictly-lower-priority gangs to claim its chips."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy}; must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError("each bundle must be a non-empty dict of resources")
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resource amounts must be >= 0")
    from .._private import worker

    client = worker.get_client()
    pg_id = client.create_placement_group(
        [dict(b) for b in bundles], strategy, name,
        tenant=tenant, priority=priority,
    )
    return PlacementGroup(PlacementGroupID(pg_id), [dict(b) for b in bundles], strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from .._private import worker

    worker.get_client().remove_placement_group(pg.id.binary())


def placement_group_table() -> dict:
    from .._private import worker

    items = worker.get_client().list_state("placement_groups")
    return {it["pg_id"]: it for it in items}


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The placement group the calling task/actor runs inside, or None.

    Parity: python/ray/util/placement_group.py
    get_current_placement_group (reference callers use it for nested
    scheduling — children placed into the parent's PG). The executor
    pins (pg_id, bundle) in a contextvar; bundles/strategy come from
    the hub's PG table.
    """
    from ..runtime_context import _current_pg

    cur = _current_pg.get()
    if cur is None:
        return None
    pg_id = cur[0]
    from .._private import worker

    if not worker.is_initialized():
        return None
    for it in worker.get_client().list_state("placement_groups"):
        if it["pg_id"] == pg_id.hex():
            return PlacementGroup(
                PlacementGroupID(pg_id), it["bundles"], it["strategy"]
            )
    return None
