"""ActorPool: map work over a fixed pool of actors.

Parity: python/ray/util/actor_pool.py:13 in the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout: float | None = None) -> Any:
        from .. import get

        if self._next_return_index >= self._next_task_index and not self._pending_submits:
            raise StopIteration("No more results to get")
        while self._next_return_index not in self._index_to_future:
            self._maybe_drain()
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return get(future, timeout=timeout)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        from .. import get, wait

        if not self.has_next():
            raise StopIteration("No more results to get")
        while not self._future_to_actor:
            self._maybe_drain()
        ready, _ = wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(idx, None)
        self._return_actor(actor)
        return get(future)

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        self._maybe_drain()

    def _maybe_drain(self) -> None:
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    @staticmethod
    def _submit_window():
        """Batched-send window for the submit burst: actor tasks can't
        share one SUBMIT_TASKS frame (each targets a different actor),
        but holding the client's count-based flush for the burst packs
        them into minimal wire frames."""
        from .._private import worker

        client = getattr(worker, "_client", None)
        if client is None:
            import contextlib

            return contextlib.nullcontext()
        return client.batch_window()

    def map(self, fn: Callable, values: Iterable[Any]):
        with self._submit_window():
            for v in values:
                self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        with self._submit_window():
            for v in values:
                self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor) -> None:
        self._return_actor(actor)
