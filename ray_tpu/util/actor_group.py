"""ActorGroup: homogeneous gang of actors addressed as one unit.

Parity: python/ray/util (ActorGroup used by train/workers utilities) —
create N actors of one class, broadcast method calls, gather results,
replace failed members.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class ActorGroup:
    def __init__(
        self,
        actor_cls,
        num_actors: int,
        *,
        actor_options: Optional[Dict[str, Any]] = None,
        init_args: tuple = (),
        init_kwargs: Optional[dict] = None,
    ):
        import ray_tpu

        self._ray = ray_tpu
        remote_cls = ray_tpu.remote(actor_cls)
        if actor_options:
            remote_cls = remote_cls.options(**actor_options)
        self._cls = remote_cls
        self._init = (init_args, dict(init_kwargs or {}))
        self.actors: List[Any] = [
            remote_cls.remote(*init_args, **(init_kwargs or {}))
            for _ in range(num_actors)
        ]

    def __len__(self) -> int:
        return len(self.actors)

    def execute_async(self, method: str, *args, **kwargs) -> List[Any]:
        return [
            getattr(a, method).remote(*args, **kwargs) for a in self.actors
        ]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        return self._ray.get(self.execute_async(method, *args, **kwargs))

    def execute_single(self, index: int, method: str, *args, **kwargs) -> Any:
        return self._ray.get(
            getattr(self.actors[index], method).remote(*args, **kwargs)
        )

    def restart_actor(self, index: int) -> None:
        """Replace one member (e.g. after ActorDiedError)."""
        try:
            self._ray.kill(self.actors[index])
        except Exception:
            pass
        args, kwargs = self._init
        self.actors[index] = self._cls.remote(*args, **kwargs)

    def shutdown(self) -> None:
        for a in self.actors:
            try:
                self._ray.kill(a)
            except Exception:
                pass
        self.actors = []
