"""Remote pdb: breakpoints inside tasks/actors, attachable from the driver.

Parity: python/ray/util/rpdb.py — the reference's ``ray.util.pdb
.set_trace()`` opens a socket-backed pdb in the worker, advertises it
in internal KV, and ``ray debug`` connects a terminal. Same design:
``set_trace()`` listens on an ephemeral TCP port, registers
``__rpdb:<uuid>`` → {host, port, pid} in hub KV, and blocks until a
debugger attaches; ``list_breakpoints()`` / ``connect()`` are the
driver side (the reference's CLI loop, minus curses).
"""

from __future__ import annotations

import json
import os
import pdb
import socket
import sys
import uuid as _uuid
from typing import Dict, List, Optional

_KV_PREFIX = b"__rpdb:"


class _RemotePdb(pdb.Pdb):
    """Pdb over an accepted socket connection (reference _PdbWrap)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._fh = sock.makefile("rw", buffering=1)
        super().__init__(stdin=self._fh, stdout=self._fh)
        self.use_rawinput = False
        self.prompt = "(ray_tpu-pdb) "

    def close(self):
        try:
            self._fh.close()
            self._sock.close()
        except OSError:
            pass

    # Detach (close the socket) when the user resumes the program —
    # there is no later point to hook: after `continue` the worker is
    # back in user code and nothing else touches the debugger object.
    def do_continue(self, arg):
        ret = super().do_continue(arg)
        if not self.breaks:
            self.close()
        return ret

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        ret = super().do_quit(arg)
        self.close()
        return ret

    do_q = do_exit = do_quit

    def __del__(self):
        self.close()


def _register(entry_uuid: str, port: int) -> None:
    from ray_tpu._private import worker

    client = worker.get_client()
    meta = {
        "host": os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1"),
        "port": port,
        "pid": os.getpid(),
    }
    client.kv_put(_KV_PREFIX + entry_uuid.encode(), json.dumps(meta).encode())


def _deregister(entry_uuid: str) -> None:
    from ray_tpu._private import worker

    try:
        worker.get_client().kv_del(_KV_PREFIX + entry_uuid.encode())
    except Exception:
        pass


def set_trace(frame=None) -> None:
    """Block this task at a breakpoint until a debugger attaches."""
    entry_uuid = _uuid.uuid4().hex[:8]
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        _register(entry_uuid, port)
        print(
            f"ray_tpu breakpoint {entry_uuid} waiting on port {port} "
            f"(pid={os.getpid()}); attach with ray_tpu.util.rpdb.connect()",
            file=sys.stderr,
        )
        conn, _ = listener.accept()
    finally:
        listener.close()
        _deregister(entry_uuid)
    dbg = _RemotePdb(conn)
    # Must be the last statement: Pdb.set_trace(frame) arms tracing and
    # returns immediately — the first stop is the next line event, which
    # must be in the caller's frame, not in a finally block here.
    dbg.set_trace(frame or sys._getframe().f_back)


def post_mortem() -> None:
    """Debug the exception currently being handled (reference
    rpdb.post_mortem via RAY_PDB_POST_MORTEM)."""
    exc = sys.exc_info()[2]
    if exc is None:
        raise RuntimeError("post_mortem() called with no active exception")
    entry_uuid = _uuid.uuid4().hex[:8]
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.bind(("0.0.0.0", 0))
    listener.listen(1)
    _register(entry_uuid, listener.getsockname()[1])
    try:
        conn, _ = listener.accept()
    finally:
        listener.close()
        _deregister(entry_uuid)
    dbg = _RemotePdb(conn)
    try:
        dbg.interaction(None, exc)
    finally:
        dbg.close()


def list_breakpoints() -> List[Dict]:
    """Active breakpoints cluster-wide (the reference's `ray debug`
    selection list)."""
    from ray_tpu._private import worker

    client = worker.get_client()
    out = []
    for key in client.kv_keys(_KV_PREFIX):
        raw = client.kv_get(key)
        if raw:
            meta = json.loads(raw)
            meta["uuid"] = key[len(_KV_PREFIX):].decode()
            out.append(meta)
    return out


def connect(
    breakpoint_uuid: Optional[str] = None,
    stdin=None,
    stdout=None,
) -> None:
    """Attach the current terminal (or the given streams — used by
    tests) to a waiting breakpoint and relay until the session ends."""
    bps = list_breakpoints()
    if not bps:
        raise RuntimeError("no active ray_tpu breakpoints")
    if breakpoint_uuid is not None:
        bps = [b for b in bps if b["uuid"] == breakpoint_uuid]
        if not bps:
            raise RuntimeError(f"breakpoint {breakpoint_uuid} not found")
    meta = bps[0]
    sock = socket.create_connection((meta["host"], meta["port"]), timeout=30)
    t = None
    try:
        stdin = stdin if stdin is not None else sys.stdin
        stdout = stdout if stdout is not None else sys.stdout
        fh = sock.makefile("rw", buffering=1)
        import threading

        def _pump_out():
            try:
                for line in fh:
                    stdout.write(line)
                    stdout.flush()
            except (OSError, ValueError):
                pass

        t = threading.Thread(target=_pump_out, daemon=True)
        t.start()
        for line in stdin:
            try:
                fh.write(line)
                fh.flush()
            except (OSError, ValueError):
                break
    finally:
        # Drain remaining debugger output first: the remote end closes
        # the socket when the session finishes (continue/quit), which
        # ends the pump; closing before that loses the tail.
        if t is not None:
            t.join(timeout=10)
        try:
            sock.close()
        except OSError:
            pass
