"""Application metrics: Counter / Gauge / Histogram.

Parity: python/ray/util/metrics.py over the reference's OpenCensus
registry (src/ray/stats/metric.h:104). TPU-native simplification: no
sidecar exporter chain — metric records batch through the client's
existing hub connection and aggregate in the hub's registry; scrape via
``ray_tpu.util.metrics.snapshot()`` or render with
``prometheus_text()`` for a /metrics endpoint.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_HIST_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0
]


class Metric:
    """Base: name + default tags; subclasses choose the aggregation."""

    _TYPE = "none"

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Optional[Sequence[str]] = None,
    ):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    @property
    def info(self) -> Dict:
        return {
            "name": self._name,
            "description": self._description,
            "tag_keys": self._tag_keys,
            "default_tags": dict(self._default_tags),
        }

    def _record(self, value: float, tags: Optional[Dict[str, str]], op: str,
                **extra):
        from .._private import protocol as P
        from .._private import worker

        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        client = worker.get_client()
        client.send_async(
            P.METRIC_RECORD,
            dict(
                extra,
                name=self._name,
                type=self._TYPE,
                description=self._description,
                value=float(value),
                tags=tuple(sorted(merged.items())),
                op=op,
            ),
        )


class Counter(Metric):
    """Monotonic cumulative count (reference: metrics.Counter)."""

    _TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc value must be > 0")
        self._record(value, tags, "add")


class Gauge(Metric):
    """Last-value-wins (reference: metrics.Gauge)."""

    _TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._record(value, tags, "set")


class Histogram(Metric):
    """Bucketed distribution (reference: metrics.Histogram)."""

    _TYPE = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[Sequence[float]] = None,
        tag_keys: Optional[Sequence[str]] = None,
    ):
        super().__init__(name, description, tag_keys)
        bounds = [float(b) for b in (boundaries or _DEFAULT_HIST_BOUNDARIES)]
        # the registry buckets observations by FIRST boundary >= value in
        # list order, which is only a histogram if boundaries ascend; and
        # Prometheus le="..." labels assume positive finite bounds. An
        # unsorted list used to mis-bucket silently.
        if not bounds:
            raise ValueError("Histogram boundaries must be non-empty")
        if any(b <= 0 for b in bounds):
            raise ValueError(
                f"Histogram boundaries must be positive, got {bounds}"
            )
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(
                "Histogram boundaries must be sorted ascending with no "
                f"duplicates, got {bounds}"
            )
        self.boundaries = bounds

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._record(value, tags, "observe", boundaries=tuple(self.boundaries))

    @property
    def info(self) -> Dict:
        d = super().info
        d["boundaries"] = list(self.boundaries)
        return d


def snapshot() -> List[Dict]:
    """Current aggregated metrics from the hub registry."""
    from .._private import worker

    return worker.get_client().list_state("metrics")


def _sanitize_name(name: str) -> str:
    """Clamp a metric name to the exposition-format charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (invalid runs become ``_``)."""
    import re

    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not name or not re.match(r"[a-zA-Z_:]", name[0]):
        name = "_" + name
    return name


def _sanitize_label_name(name: str) -> str:
    """Label names are stricter than metric names: no ``:`` allowed
    (``[a-zA-Z_][a-zA-Z0-9_]*``)."""
    import re

    name = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if not name or not re.match(r"[a-zA-Z_]", name[0]):
        name = "_" + name
    return name


def _escape_label(value) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote, and newline must be escaped or a crafted tag value
    breaks (or injects) series in the scrape."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


# last successful rendering: a scrape racing hub teardown (or hitting a
# partitioned head) serves stale-but-well-formed exposition instead of a
# 500 — Prometheus treats a failed scrape as a gap, but an exception
# here used to take the whole dashboard handler down with it
_last_exposition = ""


def prometheus_text() -> str:
    """Render the registry in Prometheus exposition format (the
    reference exports via its metrics agent to Prometheus; here the
    caller mounts this on whatever HTTP surface it likes). Degrades
    gracefully when the hub is unreachable: returns the last successful
    exposition (or an empty one) rather than raising."""
    global _last_exposition
    try:
        metrics = snapshot()
    except Exception:
        return _last_exposition
    lines: List[str] = []
    seen_help = set()
    for m in metrics:
        name = _sanitize_name(m["name"])
        if name not in seen_help:
            seen_help.add(name)
            if m.get("description"):
                lines.append(f"# HELP {name} {_escape_help(m['description'])}")
            kind = {"counter": "counter", "gauge": "gauge",
                    "histogram": "histogram"}.get(m["type"], "untyped")
            lines.append(f"# TYPE {name} {kind}")
        labels = ",".join(
            f'{_sanitize_label_name(k)}="{_escape_label(v)}"'
            for k, v in m["tags"]
        )
        suffix = "{" + labels + "}" if labels else ""
        if m["type"] == "histogram":
            acc = 0
            for bound, count in m["buckets"]:
                acc += count
                lb = ",".join(filter(None, [labels, f'le="{bound}"']))
                lines.append(f"{name}_bucket{{{lb}}} {acc}")
            lb = ",".join(filter(None, [labels, 'le="+Inf"']))
            lines.append(f"{name}_bucket{{{lb}}} {m['count']}")
            lines.append(f"{name}_sum{suffix} {m['sum']}")
            lines.append(f"{name}_count{suffix} {m['count']}")
        else:
            lines.append(f"{name}{suffix} {m['value']}")
    _last_exposition = "\n".join(lines) + "\n"
    return _last_exposition
