"""Distributed tracing: user spans AND the runtime's own spans.

Parity: python/ray/util/tracing/ — the reference hooks opentelemetry
spans around every API call and propagates the otel context in task
metadata. Here spans are framework-native and come in two layers:

**User spans** (this module's public API): a contextvar carries
(trace_id, span_id) for nesting, finished spans batch to the hub over
the client's existing connection, and they render in the same
chrome-trace ``timeline()`` as task events (cat="span").

    from ray_tpu.util import tracing

    tracing.enable()
    with tracing.span("preprocess", rows=1000):
        ...
    ctx = tracing.current_context()      # ship to another process
    # in a task:  with tracing.context(ctx), tracing.span("stage2"): ...

**Runtime spans**: with head sampling on (``RAY_TPU_TRACE_SAMPLE=0..1``,
or ``RAY_TPU_TRACING=1`` which forces 1.0), the runtime traces itself —
trace context rides SUBMIT/actor-call/GET/PUT/object-transfer messages
and every stage emits a span (client encode+send, shard ring wait,
scheduler admit/queue/spawn, worker arg-fetch/execute/result-store,
readiness push, result return), stitched into one trace per task chain.
Traces are queryable via ``list_state("traces")`` /
``ray_tpu trace <id>`` / dashboard ``GET /api/traces`` and fed through
:func:`analyze_trace`, the critical-path analyzer that names the
dominant stage. The default sample rate is 0: no context rides the
wire and no runtime span is ever built.

Clock discipline (graftlint GL008, which covers this file): span
start/end are positioned in wall time for cross-process stitching, but
every DURATION comes from ``time.monotonic()`` — each process anchors
its monotonic clock to wall time exactly once at import
(``_MONO_ANCHOR``/``_WALL_ANCHOR``) and renders a monotonic stamp as
``wall_anchor + (mono - mono_anchor)``, so an NTP step mid-span can
never produce a negative or inflated duration.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Any, Dict, List, Optional, Tuple

# one wall anchor per process: all span timestamps are monotonic stamps
# re-based onto this anchor (same-host processes share the wall clock,
# so cross-process spans land on one coherent timeline)
_MONO_ANCHOR = time.monotonic()
_WALL_ANCHOR = time.time()

_enabled = os.environ.get("RAY_TPU_TRACING", "") in ("1", "true", "yes")
# (trace_id, span_id) of the innermost open span — user spans AND the
# runtime's execute span share this, so nested submits from inside a
# traced task inherit the trace and user spans parent under it
_ctx: contextvars.ContextVar[Optional[Tuple[str, str]]] = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)


def wall_at(mono: float) -> float:
    """Render a time.monotonic() stamp as an anchored wall timestamp."""
    return _WALL_ANCHOR + (mono - _MONO_ANCHOR)


def new_span_id() -> str:
    """16-hex-char span/trace id from the per-thread entropy pool
    (_private/ids.py) — span open is a hot path under sampling, and a
    uuid4() per span costs an os.urandom syscall each."""
    from ray_tpu._private.ids import span_id_hex

    return span_id_hex()


def runtime_sample_rate() -> float:
    """Head-sampling probability for RUNTIME spans. RAY_TPU_TRACING=1
    forces 1.0; otherwise RAY_TPU_TRACE_SAMPLE in [0, 1]; default 0
    keeps the hot path free of any tracing work."""
    if os.environ.get("RAY_TPU_TRACING", "") in ("1", "true", "yes"):
        return 1.0
    raw = os.environ.get("RAY_TPU_TRACE_SAMPLE", "")
    if not raw:
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(1.0, max(0.0, rate))


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) to hand to another process (the reference
    propagates the otel context in task metadata)."""
    return _ctx.get()


@contextlib.contextmanager
def context(ctx: Optional[Tuple[str, str]]):
    """Adopt a remote parent context for spans opened inside."""
    token = _ctx.set(tuple(ctx) if ctx else None)
    try:
        yield
    finally:
        _ctx.reset(token)


def push_context(ctx: Tuple[str, str]):
    """Non-contextmanager form for the runtime (worker execute scope):
    returns the reset token for pop_context."""
    return _ctx.set(tuple(ctx))


def pop_context(token) -> None:
    _ctx.reset(token)


def make_runtime_record(
    name: str,
    stage: str,
    trace_id: str,
    parent_id: Optional[str],
    t0_mono: float,
    t1_mono: float,
    span_id: Optional[str] = None,
    node_id: Optional[str] = None,
    attrs: Optional[Dict[str, Any]] = None,
    **extra: Any,
) -> Dict[str, Any]:
    """Build one runtime span record from monotonic stamps. The record
    schema matches user spans, plus attrs["stage"] — the key the
    critical-path analyzer groups by. Attributes whose keys collide
    with the positional params (e.g. "name") go through `attrs`."""
    a = {"stage": stage}
    for src in (attrs, extra):
        if src:
            for k, v in src.items():
                a[k] = str(v)
    return {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id or new_span_id(),
        "parent_id": parent_id,
        "start": wall_at(t0_mono),
        "end": wall_at(t1_mono),
        "pid": os.getpid(),
        "node_id": node_id or os.environ.get("RAY_TPU_NODE_ID", "head"),
        "attrs": a,
    }


def _emit(record: Dict[str, Any]) -> None:
    from ray_tpu._private import protocol as P
    from ray_tpu._private import worker

    if not worker.is_initialized():
        return
    try:
        worker.get_client().send_async(P.SPAN_RECORD, record)
    except Exception:
        pass  # tracing must never take down the traced code


@contextlib.contextmanager
def span(name: str, **attrs: Any):
    """Record a span around the block (no-op unless tracing is on)."""
    if not _enabled:
        yield None
        return
    parent = _ctx.get()
    trace_id = parent[0] if parent else new_span_id()
    span_id = new_span_id()
    token = _ctx.set((trace_id, span_id))
    start = time.monotonic()
    error: Optional[str] = None
    try:
        yield (trace_id, span_id)
    except BaseException as exc:
        error = type(exc).__name__
        raise
    finally:
        _ctx.reset(token)
        end = time.monotonic()
        record = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent[1] if parent else None,
            "start": wall_at(start),
            "end": wall_at(end),
            "pid": os.getpid(),
            "node_id": os.environ.get("RAY_TPU_NODE_ID", "head"),
            "attrs": {k: str(v) for k, v in attrs.items()},
        }
        if error is not None:
            record["attrs"]["error"] = error
        _emit(record)


def traced(name: Optional[str] = None):
    """Decorator form: ``@tracing.traced()`` wraps calls in a span."""

    def wrap(fn):
        import functools
        import inspect

        span_name = name or getattr(fn, "__qualname__", fn.__name__)

        if inspect.iscoroutinefunction(fn):
            # the span must cover the awaited body, not the instant
            # coroutine construction — and the context must be live
            # while the body executes so child spans parent correctly
            @functools.wraps(fn)
            async def ainner(*args, **kwargs):
                with span(span_name):
                    return await fn(*args, **kwargs)

            return ainner

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(span_name):
                return fn(*args, **kwargs)

        return inner

    return wrap


# --------------------------------------------------- critical-path analysis
# Stage catalog: every runtime span carries attrs["stage"] drawn from
# this set. Precedence resolves overlap — when two stages cover the same
# instant (a worker spawn inside the queue wait; client.submit
# overlapping the hub's admit), the timeline slice is charged to the
# HIGHER-precedence (more specific) stage, so per-stage durations
# partition the trace instead of double-counting.
STAGE_PRECEDENCE: Dict[str, int] = {
    "submit": 10,        # client: encode + hand the SUBMIT to the wire
    "ring_wait": 40,     # sharded hub: decoded frame parked on the SPSC ring
    "admit": 50,         # hub: dep registration + quota admission
    "queue_wait": 20,    # hub: runnable-queue wait, admit -> dispatch
    "spawn": 30,         # hub: worker process spawn inside the queue wait
    "arg_fetch": 60,     # worker: decode + dependency resolution
    "execute": 60,       # worker: the user function body
    "result_store": 60,  # worker: encode + store returns
    "complete": 50,      # hub: TASK_DONE handling
    "ready_push": 55,    # hub: readiness push to subscribed waiters
    "result_return": 15, # client: tail of get() after the hub finished
    "transfer": 45,      # object plane: segment fetch (direct or relay)
    "put": 35,           # put path (client encode/stream + hub handler)
    "get": 35,           # hub GET handler
    # ---- serve data plane (serve/_private/observability.py). The serve
    # spans ENVELOP the task-layer spans of the underlying actor call,
    # so precedence places them around the existing catalog instead of
    # double-counting it: serve.queue_wait sits BELOW every task stage
    # (it spans enqueue -> replica start, and must only be charged the
    # genuinely-waiting slices no narrower stage covers), serve.execute
    # sits ABOVE worker execute (the replica's request handling IS the
    # user body there), and batch-wait/multiplex-swap sit above
    # serve.execute so time parked inside the handler is named for what
    # it actually was. dominant_stage then answers the serving question
    # directly: router vs queue vs batch-wait vs execute.
    "serve.queue_wait": 5,       # enqueue -> replica start, uncovered gap
    "serve.proxy_recv": 22,      # ingress: recv + parse + route match
    "serve.response_return": 24, # ingress: response encode + write
    "serve.route": 25,           # handle: replica wait + pick + dispatch
    "serve.execute": 70,         # replica: the user callable
    "serve.batch_wait": 75,      # @serve.batch: parked awaiting a batch
    "serve.multiplex_swap": 78,  # multiplex: LRU-miss model load
    # zero-copy payload plane (serve/_private/payloads.py):
    # payload_put wraps the handle-side spill (put_value of the raw
    # body) — above put=35 so the slice names the serve intent, below
    # ring/transfer so genuine object-plane work keeps its name;
    # payload_fetch wraps the replica-side bulk resolve — above
    # serve.execute=70 (it happens inside the handler envelope and is
    # I/O, not user code), below batch_wait so parked members still
    # charge their park correctly.
    "serve.payload_put": 38,     # handle: spill request body to object plane
    "serve.payload_fetch": 72,   # replica: bulk-resolve payload refs
    # ---- Podracer RL loops (rllib/podracer). These are user-level
    # spans emitted inside the actor/learner task bodies, so they sit
    # ABOVE worker execute (60): within a Podracer task the RL phase is
    # the more specific name for the slice. env_step (the acting scan)
    # vs learner_update (the SGD step) is the question analyze_trace
    # answers — actor-bound or learner-bound. traj_handoff (learner-
    # side ingestion of handed-off fragments) and param_sync (actor-
    # side KV fetch / learner-side KV publish) name the cross-slice
    # coupling costs; they sit above env_step/learner_update because
    # both occur as narrower phases inside the same task bodies and
    # must not be double-charged to the enclosing RL phase.
    "podracer.env_step": 71,
    "podracer.learner_update": 71,
    "podracer.traj_handoff": 74,
    "podracer.param_sync": 74,
}


def _stage_of(s: Dict[str, Any]) -> Optional[str]:
    return (s.get("attrs") or {}).get("stage")


def analyze_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Critical-path breakdown of one trace: which stage did the time
    go to? Overlapping stage spans are resolved by STAGE_PRECEDENCE
    (each instant charged to exactly one stage), ``result_return`` is
    recomputed as the tail of the enveloping client get span past the
    last runtime stage, and whatever no span covers is reported as
    ``untracked_s`` — stages + untracked always sum to end_to_end_s.

    The input is whatever the hub retained — a trace truncated by
    eviction or a crashing process can contain orphan spans (parent_id
    never recorded; irrelevant here, the sweep does not walk parents),
    spans missing or corrupting their start/end stamps, and
    zero-duration stages. Malformed spans are dropped (counted in
    ``malformed_spans``) and the analysis proceeds on the rest — a
    partial report, never an exception."""
    raw = spans

    def _ok(s: Any) -> bool:
        if not isinstance(s, dict):
            return False
        a, b = s.get("start"), s.get("end")
        return (
            isinstance(a, (int, float)) and isinstance(b, (int, float))
            and not isinstance(a, bool) and not isinstance(b, bool)
            and b >= a
        )

    spans = [s for s in raw if _ok(s)]
    if not spans:
        return {"trace_id": None, "n_spans": len(raw), "end_to_end_s": 0.0,
                "stages": {}, "dominant_stage": None, "untracked_s": 0.0,
                "processes": [], "malformed_spans": len(raw)}
    t_start = min(s["start"] for s in spans)
    t_end = max(s["end"] for s in spans)
    e2e = max(0.0, t_end - t_start)
    intervals: List[Tuple[float, float, str]] = []
    tails: List[Tuple[float, float]] = []  # result_return envelopes
    last_stage_end = t_start
    for s in spans:
        stage = _stage_of(s)
        if stage is None:
            continue  # user span: positions in the trace, not a stage
        if stage == "result_return":
            # client.get envelops the whole wait; only its tail past
            # the last runtime stage is genuinely "returning the result"
            tails.append((s["start"], s["end"]))
            continue
        intervals.append((s["start"], s["end"], stage))
        last_stage_end = max(last_stage_end, s["end"])
    if tails:
        # clamp to the LATEST get span's own start too: a get() issued
        # long after the task finished must not book the driver's idle
        # time between completion and the call as result_return
        tail_start, tail_end = max(tails, key=lambda se: se[1])
        tail_start = max(tail_start, last_stage_end)
        if tail_end > tail_start:
            intervals.append((tail_start, tail_end, "result_return"))
    # sweep line: charge each elementary slice to the highest-precedence
    # active stage
    stages: Dict[str, float] = {}
    covered = 0.0
    if intervals:
        edges = sorted({t for iv in intervals for t in iv[:2]})
        for lo, hi in zip(edges, edges[1:]):
            if hi <= lo:
                continue
            active = [st for (a, b, st) in intervals if a <= lo and b >= hi]
            if not active:
                continue
            winner = max(active, key=lambda st: STAGE_PRECEDENCE.get(st, 0))
            stages[winner] = stages.get(winner, 0.0) + (hi - lo)
            covered += hi - lo
    dominant = max(stages, key=stages.get) if stages else None
    return {
        "trace_id": spans[0].get("trace_id"),
        "n_spans": len(raw),
        "malformed_spans": len(raw) - len(spans),
        "end_to_end_s": e2e,
        "stages": {
            st: {"dur_s": dur, "share": (dur / e2e) if e2e > 0 else 0.0}
            for st, dur in sorted(
                stages.items(), key=lambda kv: -kv[1]
            )
        },
        "dominant_stage": dominant,
        "untracked_s": max(0.0, e2e - covered),
        "processes": sorted(
            {f"{s.get('node_id', '?')}/pid={s.get('pid', '?')}"
             for s in spans}
        ),
    }


__all__ = [
    "enable",
    "disable",
    "is_enabled",
    "span",
    "traced",
    "current_context",
    "context",
    "push_context",
    "pop_context",
    "new_span_id",
    "runtime_sample_rate",
    "make_runtime_record",
    "wall_at",
    "analyze_trace",
    "STAGE_PRECEDENCE",
]
