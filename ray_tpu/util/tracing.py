"""Distributed tracing spans.

Parity: python/ray/util/tracing/ — the reference hooks opentelemetry
spans around API calls and ships them to a collector. Here spans are
framework-native: a contextvar carries (trace_id, span_id) for
nesting, finished spans batch to the hub over the client's existing
connection, and they render in the same chrome-trace ``timeline()``
as task events (cat="span"), so one Perfetto view shows user spans
over the scheduler's task rows.

    from ray_tpu.util import tracing

    tracing.enable()
    with tracing.span("preprocess", rows=1000):
        ...
    ctx = tracing.current_context()      # ship to another process
    # in a task:  with tracing.context(ctx), tracing.span("stage2"): ...

Enable globally with RAY_TPU_TRACING=1 (workers inherit the env).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
import uuid
from typing import Any, Dict, Optional, Tuple

_enabled = os.environ.get("RAY_TPU_TRACING", "") in ("1", "true", "yes")
# (trace_id, span_id) of the innermost open span
_ctx: contextvars.ContextVar[Optional[Tuple[str, str]]] = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) to hand to another process (the reference
    propagates the otel context in task metadata)."""
    return _ctx.get()


@contextlib.contextmanager
def context(ctx: Optional[Tuple[str, str]]):
    """Adopt a remote parent context for spans opened inside."""
    token = _ctx.set(tuple(ctx) if ctx else None)
    try:
        yield
    finally:
        _ctx.reset(token)


def _emit(record: Dict[str, Any]) -> None:
    from ray_tpu._private import worker

    if not worker.is_initialized():
        return
    try:
        worker.get_client().send_async("span_record", record)
    except Exception:
        pass  # tracing must never take down the traced code


@contextlib.contextmanager
def span(name: str, **attrs: Any):
    """Record a span around the block (no-op unless tracing is on)."""
    if not _enabled:
        yield None
        return
    parent = _ctx.get()
    trace_id = parent[0] if parent else uuid.uuid4().hex[:16]
    span_id = uuid.uuid4().hex[:16]
    token = _ctx.set((trace_id, span_id))
    start = time.time()
    error: Optional[str] = None
    try:
        yield (trace_id, span_id)
    except BaseException as exc:
        error = type(exc).__name__
        raise
    finally:
        _ctx.reset(token)
        record = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent[1] if parent else None,
            "start": start,
            "end": time.time(),
            "pid": os.getpid(),
            "node_id": os.environ.get("RAY_TPU_NODE_ID", "head"),
            "attrs": {k: str(v) for k, v in attrs.items()},
        }
        if error is not None:
            record["attrs"]["error"] = error
        _emit(record)


def traced(name: Optional[str] = None):
    """Decorator form: ``@tracing.traced()`` wraps calls in a span."""

    def wrap(fn):
        import functools
        import inspect

        span_name = name or getattr(fn, "__qualname__", fn.__name__)

        if inspect.iscoroutinefunction(fn):
            # the span must cover the awaited body, not the instant
            # coroutine construction — and the context must be live
            # while the body executes so child spans parent correctly
            @functools.wraps(fn)
            async def ainner(*args, **kwargs):
                with span(span_name):
                    return await fn(*args, **kwargs)

            return ainner

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(span_name):
                return fn(*args, **kwargs)

        return inner

    return wrap


__all__ = [
    "enable",
    "disable",
    "is_enabled",
    "span",
    "traced",
    "current_context",
    "context",
]
