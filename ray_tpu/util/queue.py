"""Distributed Queue backed by an async actor.

Parity: python/ray/util/queue.py — same API (put/get with block/timeout,
put_nowait/get_nowait, qsize/empty/full), implemented over an asyncio
actor so many producers/consumers block server-side without tying up
worker threads (the reference does exactly this with an async _QueueActor).
"""

from __future__ import annotations

from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        import asyncio

        self._q: "asyncio.Queue" = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        import asyncio

        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except Exception:
            return False

    async def get(self, timeout: Optional[float] = None):
        import asyncio

        if timeout is None:
            return True, await self._q.get()
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except Exception:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        import ray_tpu

        self.maxsize = maxsize
        cls = ray_tpu.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self.actor = cls.remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None) -> None:
        import ray_tpu

        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full(f"put timed out after {timeout}s")

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import ray_tpu

        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty(f"get timed out after {timeout}s")
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        for item in items:
            self.put_nowait(item)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return [self.get_nowait() for _ in range(num_items)]

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self.actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self) -> None:
        import ray_tpu

        ray_tpu.kill(self.actor)
