"""Public TPU pod helpers.

Parity: python/ray/util/accelerators/tpu.py:7-33
(get_current_pod_name / get_current_pod_worker_count /
get_num_tpu_chips_on_node over TPUAcceleratorManager). Detection reads
the standard TPU VM environment (TPU_NAME, TPU_WORKER_HOSTNAMES,
TPU_ACCELERATOR_TYPE / PALLAS_AXON_TPU_GEN) — the GCE metadata server
the reference also falls back to is unreachable in air-gapped pods, so
env is authoritative here.
"""

from __future__ import annotations

import os
from typing import Optional

# chips per host by generation (public TPU VM shapes)
_CHIPS_PER_HOST = {"v4": 4, "v5e": 8, "v5p": 4, "v5litepod": 8, "v6e": 8}


def get_current_pod_name() -> Optional[str]:
    """The TPU pod's name resource (gang-affinity key: the reference
    exposes TPU-{name} as a custom resource for pod-wide placement)."""
    name = os.environ.get("TPU_NAME") or os.environ.get("TPU_POD_NAME")
    return name or None


def get_current_pod_worker_count() -> int:
    """Number of hosts in this pod (1 on a single-host slice)."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hosts:
        return len([h for h in hosts.split(",") if h.strip()])
    return 1


def get_accelerator_type() -> Optional[str]:
    """e.g. "v5e", "v5p" — from TPU_ACCELERATOR_TYPE ("v5litepod-16")
    or the axon gen env."""
    acc = os.environ.get("TPU_ACCELERATOR_TYPE")
    if acc:
        return acc.split("-")[0]
    gen = os.environ.get("PALLAS_AXON_TPU_GEN")
    if gen:
        return gen.split(":")[0]
    return None


def get_num_tpu_chips_on_node() -> int:
    """Chips visible on this host: explicit env, else jax device count
    (when jax is already up), else the generation's standard host shape."""
    env = os.environ.get("RAY_TPU_NUM_TPUS") or os.environ.get("TPU_NUM_DEVICES")
    if env:
        return int(env)
    from ray_tpu._private.jax_utils import safe_tpu_device_count

    n = safe_tpu_device_count()
    if n:
        return n
    gen = get_accelerator_type()
    if gen:
        acc = os.environ.get("TPU_ACCELERATOR_TYPE", "")
        if "-" in acc:
            # "v5litepod-16" = 16 chips across the pod; per host:
            total = int(acc.split("-")[-1])
            return max(1, total // get_current_pod_worker_count())
        return _CHIPS_PER_HOST.get(gen, 4)
    return 0
