"""multiprocessing.Pool drop-in over the distributed runtime.

Parity: python/ray/util/multiprocessing/ — the Pool API (map/starmap/
apply/async variants/imap) executing on cluster workers instead of local
forks, so existing Pool code scales past one host unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        import ray_tpu

        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu

        done, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0
        )
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            # multiprocessing contract: querying an unfinished result is
            # an error, not False
            raise ValueError("AsyncResult is not ready")
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    """Process pool on cluster workers (reference: util/multiprocessing).

    ``processes`` bounds in-flight submission on the lazy/sync paths
    (map/starmap/apply/imap*); the *_async methods submit their whole
    input eagerly, matching their return-immediately contract. The
    runtime's worker pool does the real scaling."""

    def __init__(self, processes: Optional[int] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._ray = ray_tpu
        self._processes = processes
        self._closed = False

    # -- sync ----------------------------------------------------------
    def map(self, func: Callable, iterable: Iterable) -> List[Any]:
        return list(self.imap(func, iterable))

    def starmap(self, func: Callable, iterable: Iterable) -> List[Any]:
        return list(self.imap(lambda pair: func(*pair), iterable))

    def apply(self, func: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(func, args, kwds).get()

    # -- async ---------------------------------------------------------
    def _remote(self, func):
        import ray_tpu

        return ray_tpu.remote(func)

    def map_async(self, func: Callable, iterable: Iterable) -> AsyncResult:
        self._check_open()
        rf = self._remote(func)
        # whole input in one SUBMIT_TASKS frame; (x,) keeps single-arg
        # semantics even when x is itself a tuple
        return AsyncResult(rf.map([(x,) for x in iterable]), single=False)

    def starmap_async(self, func: Callable, iterable: Iterable) -> AsyncResult:
        self._check_open()
        rf = self._remote(func)
        return AsyncResult(rf.map([tuple(x) for x in iterable]), single=False)

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        self._check_open()
        rf = self._remote(func)
        return AsyncResult([rf.remote(*args, **(kwds or {}))], single=True)

    def _window(self) -> int:
        return self._processes or 64

    def imap(self, func: Callable, iterable: Iterable):
        """Lazy, windowed (stdlib imap consumes the iterable
        incrementally — so does this, keeping <= window in flight)."""
        self._check_open()
        from collections import deque

        rf = self._remote(func)
        it = iter(iterable)
        inflight: deque = deque()
        first: List[Any] = []
        try:
            while len(first) < self._window():
                first.append((next(it),))
        except StopIteration:
            pass
        # the initial window is the bursty part — ship it as one frame
        inflight.extend(rf.map(first))
        while inflight:
            yield self._ray.get(inflight.popleft())
            try:
                inflight.append(rf.remote(next(it)))
            except StopIteration:
                pass

    def imap_unordered(self, func: Callable, iterable: Iterable):
        self._check_open()
        rf = self._remote(func)
        it = iter(iterable)
        pending = set()
        exhausted = False
        while True:
            refill: List[Any] = []
            while not exhausted and len(pending) + len(refill) < self._window():
                try:
                    refill.append((next(it),))
                except StopIteration:
                    exhausted = True
            if refill:
                pending.update(rf.map(refill))
            if not pending:
                return
            done, _ = self._ray.wait(list(pending), num_returns=1, timeout=60)
            for ref in done:
                pending.discard(ref)
                yield self._ray.get(ref)

    # -- lifecycle ------------------------------------------------------
    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
