"""Utilities: placement groups, scheduling strategies, actor pools."""

from .actor_pool import ActorPool
from .placement_group import (
    PlacementGroup,
    get_current_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "placement_group_table",
    "get_current_placement_group",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
]
