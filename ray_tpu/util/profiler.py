"""Cluster-wide profiler surface: folded stacks, tops, remote dumps.

Parity: `ray stack` / py-spy dashboards (reference: dashboard/modules/
reporter's profiling endpoints) re-done over the hub's own aggregation
point. Every runtime process runs the in-process sampler from
``ray_tpu._private.profiling`` (opt-in via RAY_TPU_PROFILE_HZ); batches
fold at the hub; this module is the read side:

- :func:`snapshot` — the raw folded rows (list_state("profile")).
- :func:`profile` — window a snapshot pair over ``duration_s`` and diff
  them, so the report covers exactly the window (the hub's table is
  cumulative). Backs ``ray_tpu profile``.
- :func:`fold_lines` — flamegraph collapsed format, one
  ``prefix;stack count`` line per row, ready for flamegraph.pl /
  speedscope.
- :func:`top` — aggregate sample counts by stage / task / thread /
  stack for a terminal table.
- :func:`stack` — on-demand all-thread stack dump of the hub or a
  worker (works with the profiler OFF).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


# rows are keyed by everything except the sample count
_KEY = ("pid", "kind", "thread", "stage", "task_id", "stack")


def _row_key(row: dict) -> Tuple:
    return tuple(row.get(k) for k in _KEY)


def snapshot() -> List[dict]:
    """Cumulative folded samples from the hub (+ per-process meta rows
    flagged ``proc=True``). Empty when no sampler is running."""
    return _client().list_state("profile")


def diff(before: List[dict], after: List[dict]) -> List[dict]:
    """Sample-count delta between two snapshots — the activity that
    happened in between. Meta rows pass through from ``after``."""
    base: Dict[Tuple, int] = {}
    for row in before:
        if not row.get("proc"):
            base[_row_key(row)] = row.get("samples", 0)
    out: List[dict] = []
    for row in after:
        if row.get("proc"):
            out.append(dict(row))
            continue
        delta = row.get("samples", 0) - base.get(_row_key(row), 0)
        if delta > 0:
            out.append(dict(row, samples=delta))
    return out


def profile(duration_s: float = 5.0) -> List[dict]:
    """Collect ``duration_s`` seconds of cluster profile: snapshot,
    wait, snapshot, diff. Requires a sampler to be on somewhere
    (RAY_TPU_PROFILE_HZ > 0) — with none running both snapshots are
    empty and so is the result."""
    before = snapshot()
    time.sleep(max(0.0, float(duration_s)))
    return diff(before, snapshot())


def fold_lines(rows: List[dict], with_task_names: bool = True) -> List[str]:
    """Flamegraph collapsed format. Each row renders as

        <kind>:<pid>;<thread>;<stage>[;task:<id> (<name>)];<stack> <n>

    so flamegraphs group by process, then thread domain, then runtime
    stage, with the per-task split inside."""
    lines: List[str] = []
    for row in rows:
        if row.get("proc"):
            continue
        parts = [
            f"{row.get('kind', '?')}:{row.get('pid', '?')}",
            str(row.get("thread", "?")),
            str(row.get("stage", "?")),
        ]
        task = row.get("task_id")
        if task:
            name = row.get("task_name")
            label = f"task:{task[:8]}"
            if with_task_names and name:
                label += f" ({name})"
            parts.append(label)
        stack = row.get("stack")
        if stack:
            parts.append(stack)
        lines.append(";".join(parts) + f" {row.get('samples', 0)}")
    return lines


def top(rows: List[dict], by: str = "stage", n: int = 20) -> List[dict]:
    """Aggregate sample counts by one dimension: "stage", "task",
    "thread", "kind", or "stack" (leaf frame). Returns rows sorted by
    samples descending with a share-of-total ratio."""
    agg: Dict[str, int] = {}
    total = 0
    for row in rows:
        if row.get("proc"):
            continue
        samples = row.get("samples", 0)
        total += samples
        if by == "task":
            key = row.get("task_id") or "(no task)"
            name = row.get("task_name")
            if name and row.get("task_id"):
                key = f"{key[:8]} ({name})"
        elif by == "stack":
            stack = row.get("stack") or ""
            key = stack.rsplit(";", 1)[-1] or "(no stack)"
        else:
            key = str(row.get(by, "?"))
        agg[key] = agg.get(key, 0) + samples
    out = [
        {by: key, "samples": count,
         "share": (count / total) if total else 0.0}
        for key, count in sorted(agg.items(), key=lambda kv: -kv[1])
    ]
    return out[:n]


def overhead(rows: Optional[List[dict]] = None) -> List[dict]:
    """Per-process sampler meta rows (kind, hz, self-overhead ratio,
    drop count) — the profiler watching itself."""
    if rows is None:
        rows = snapshot()
    return [dict(r) for r in rows if r.get("proc")]


def stack(target: str = "hub", timeout: float = 10.0) -> dict:
    """All-thread stack dump of one process, no sampler needed:
    "hub" (or a pid matching the hub's) dumps the hub process inline;
    anything else resolves a worker by id prefix or reported pid and
    round-trips a STACK_DUMP through its control connection. Returns
    ``{"target", "pid", "threads": [{thread, ident, daemon, frames}]}``
    or an ``{"error": ...}`` payload on timeout / unknown target."""
    return _client().stack_dump(target, timeout=timeout)


def format_stack(reply: dict) -> str:
    """Render a :func:`stack` reply the way `py-spy dump` reads: one
    block per thread, innermost frame last."""
    lines: List[str] = []
    header = f"==== {reply.get('target', '?')} pid={reply.get('pid', '?')}"
    lines.append(header)
    if reply.get("error"):
        lines.append(f"  error: {reply['error']}")
    for t in reply.get("threads", ()):
        flags = " [daemon]" if t.get("daemon") else ""
        lines.append(f"-- thread {t.get('thread')} (ident={t.get('ident')})"
                     f"{flags}")
        for frame_line in t.get("frames", ()):
            lines.append("  " + frame_line)
    return "\n".join(lines) + "\n"
