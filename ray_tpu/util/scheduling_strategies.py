"""Scheduling strategies. Parity: python/ray/util/scheduling_strategies.py."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    """Schedule a task/actor into a placement group bundle."""

    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: Optional[bool] = None,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    """Pin to a node (single-host runtime: always the local node)."""

    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[dict] = None, soft: Optional[dict] = None):
        self.hard = hard or {}
        self.soft = soft or {}


# "DEFAULT" / "SPREAD" string strategies are also accepted, matching the
# reference's hybrid/spread policy names (src/ray/raylet/scheduling/policy/).
DEFAULT_SCHEDULING_STRATEGY = "DEFAULT"
SPREAD_SCHEDULING_STRATEGY = "SPREAD"
