"""ray_tpu.util.collective — collective communication on TPU meshes.

Parity: python/ray/util/collective/__init__.py. Backends: "xla"
(in-process device mesh, compiled ICI collectives) and "store"
(cross-process via a named coordinator actor).
"""

from .collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_group_handle,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from .types import Backend, ReduceOp

__all__ = [
    "Backend",
    "ReduceOp",
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "create_collective_group",
    "destroy_collective_group",
    "get_collective_group_size",
    "get_group_handle",
    "get_rank",
    "init_collective_group",
    "is_group_initialized",
    "recv",
    "reduce",
    "reducescatter",
    "send",
]
