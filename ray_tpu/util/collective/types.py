"""Collective types: reduce ops, backends, group descriptors.

Parity: python/ray/util/collective/types.py in the reference (ReduceOp,
Backend validation, *Options dataclasses). TPU-native difference: the
primary backend is "xla" — collectives compile to XLA programs over a
device mesh — rather than NCCL; "store" is the CPU/cross-process
fallback (the reference's gloo role).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, List, Optional


class ReduceOp(enum.Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVERAGE = 4


class Backend:
    """Validated backend name (reference: types.py Backend class).

    - ``XLA``: in-process device mesh; ops are cached shape-specialized
      jitted programs; collectives ride ICI on real hardware.
    - ``STORE``: cross-process eager collectives rendezvoused through a
      named coordinator actor (the reference's gloo/NCCLUniqueIDStore
      pattern, nccl_collective_group.py:29-92).
    """

    XLA = "xla"
    STORE = "store"
    NCCL = "nccl"  # rejected with a helpful error (no NVIDIA on TPU)
    GLOO = "gloo"  # alias of STORE

    def __new__(cls, name: str):
        backend = name.lower() if isinstance(name, str) else name
        if backend == cls.GLOO:
            backend = cls.STORE
        if backend == cls.NCCL:
            raise ValueError(
                "NCCL is a GPU backend; on TPU use backend='xla' (ICI mesh) "
                "or backend='store' (CPU/cross-process)."
            )
        if backend not in (cls.XLA, cls.STORE):
            raise ValueError(f"Unsupported collective backend: {name!r}")
        return backend


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000
