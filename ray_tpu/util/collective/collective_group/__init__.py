from .base import BaseGroup

__all__ = ["BaseGroup"]
