"""XLA collective group: eager collectives as cached jitted programs.

The TPU-native replacement for the reference's NCCLGroup
(python/ray/util/collective/collective_group/nccl_collective_group.py:128).
Where NCCL caches a communicator per device list (:402-432), we cache a
*compiled XLA program* per (op, shape, dtype, reduce_op): the group is a
1-D `jax.sharding.Mesh` over its devices, each eager call assembles the
per-device shards into one sharded jax.Array and runs a shard_map'd
psum/all_gather/psum_scatter/ppermute over the group axis — XLA lowers
those to ICI collectives on real TPU slices.

This is the single-controller, in-process path (one Python process
driving all chips of a host/slice — JAX's native model). The
cross-process path is StoreGroup.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5: top-level export, replication check named check_vma
    from jax import shard_map as _shard_map_impl

    _SHARD_MAP_NO_CHECK = {"check_vma": False}
except ImportError:  # jax 0.4.x: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_NO_CHECK = {"check_rep": False}


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """Version-spanning shard_map with the replication check off (the
    eager collective bodies intentionally return per-rank values that
    the checker would reject as unreplicated)."""
    kwargs.pop("check_vma", None)
    kwargs.pop("check_rep", None)
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **_SHARD_MAP_NO_CHECK, **kwargs,
    )

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)
from .base import BaseGroup

_AXIS = "group"


def _reduce_fn(op: ReduceOp):
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return partial(jax.lax.psum, axis_name=_AXIS)
    if op == ReduceOp.MAX:
        return partial(jax.lax.pmax, axis_name=_AXIS)
    if op == ReduceOp.MIN:
        return partial(jax.lax.pmin, axis_name=_AXIS)
    if op == ReduceOp.PRODUCT:
        # No pprod primitive; log-space is lossy — use allgather+prod.
        def pprod(x, axis_name=_AXIS):
            gathered = jax.lax.all_gather(x, axis_name)
            return jnp.prod(gathered, axis=0)

        return pprod
    raise ValueError(f"unsupported reduce op {op}")


class XlaGroup(BaseGroup):
    """A collective group over N in-process devices.

    Tensor convention for eager ops: a list of N per-rank arrays (rank i
    lives on device i of the group), all the same shape/dtype. Each op
    returns a new list of N arrays, one per device. A single sharded
    jax.Array whose leading-axis sharding matches the group mesh is also
    accepted and returned as such.
    """

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        devices: Sequence[jax.Device] | None = None,
    ):
        super().__init__(world_size, rank, group_name)
        if devices is None:
            devices = jax.devices()[:world_size]
        if len(devices) != world_size:
            raise ValueError(
                f"group of world_size {world_size} needs {world_size} devices, "
                f"got {len(devices)}"
            )
        self._devices = list(devices)
        self._mesh = Mesh(np.asarray(self._devices), (_AXIS,))
        # (op_name, shape, dtype, extra) -> compiled callable
        self._programs: Dict[Tuple, Any] = {}

    @property
    def backend(self) -> str:
        return "xla"

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def destroy_group(self) -> None:
        self._programs.clear()

    # -- shard assembly ------------------------------------------------

    def _stack(self, tensors: List[Any]) -> jax.Array:
        """Per-rank tensors -> one array [world, ...] sharded over the mesh."""
        if len(tensors) != self._world_size:
            raise ValueError(
                f"expected {self._world_size} per-rank tensors, got {len(tensors)}"
            )
        shape = jnp.shape(tensors[0])
        shards = [
            jax.device_put(jnp.asarray(t)[None], d)
            for t, d in zip(tensors, self._devices)
        ]
        sharding = NamedSharding(self._mesh, P(_AXIS))
        return jax.make_array_from_single_device_arrays(
            (self._world_size, *shape), sharding, shards
        )

    def _unstack(self, arr: jax.Array) -> List[jax.Array]:
        shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start)
        return [s.data[0] for s in shards]

    def _program(self, key: Tuple, build):
        prog = self._programs.get(key)
        if prog is None:
            prog = build()
            self._programs[key] = prog
        return prog

    def _run(self, name: str, tensors, body, out_specs=P(_AXIS)):
        """Compile-and-cache an eager collective: body runs per-shard
        under shard_map with axis `group`."""
        is_list = isinstance(tensors, (list, tuple))
        arr = self._stack(list(tensors)) if is_list else tensors
        key = (name, arr.shape, str(arr.dtype))
        prog = self._program(
            key,
            lambda: jax.jit(
                shard_map(
                    body,
                    mesh=self._mesh,
                    in_specs=P(_AXIS),
                    out_specs=out_specs,
                    check_vma=False,
                )
            ),
        )
        out = prog(arr)
        return self._unstack(out) if is_list else out

    # -- collectives ---------------------------------------------------

    def allreduce(self, tensors, opts: AllReduceOptions = AllReduceOptions()):
        red = _reduce_fn(opts.reduceOp)
        world = self._world_size

        def body(x):  # x: [1, ...] local shard
            y = red(x)
            if opts.reduceOp == ReduceOp.AVERAGE:
                y = y / world
            return y

        return self._run(("allreduce", opts.reduceOp), tensors, body)

    def reduce(self, tensors, opts: ReduceOptions = ReduceOptions()):
        red = _reduce_fn(opts.reduceOp)
        root = opts.root_rank

        def body(x):
            y = red(x)
            if opts.reduceOp == ReduceOp.AVERAGE:
                y = y / self._world_size
            idx = jax.lax.axis_index(_AXIS)
            return jnp.where(idx == root, y, x)

        return self._run(("reduce", opts.reduceOp, root), tensors, body)

    def broadcast(self, tensors, opts: BroadcastOptions = BroadcastOptions()):
        root = opts.root_rank
        world = self._world_size

        def body(x):
            # one-hot psum: every rank gets root's shard
            idx = jax.lax.axis_index(_AXIS)
            contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
            return jax.lax.psum(contrib, _AXIS)

        return self._run(("broadcast", root), tensors, body)

    def allgather(self, tensors, opts: AllGatherOptions = AllGatherOptions()):
        """Each rank contributes [k...]; each rank receives [world, k...]."""
        is_list = isinstance(tensors, (list, tuple))
        arr = self._stack(list(tensors)) if is_list else tensors
        key = ("allgather", arr.shape, str(arr.dtype))
        world = self._world_size

        def body(x):  # x: [1, k...] -> [world, k...] per rank
            return jax.lax.all_gather(x[0], _AXIS)

        prog = self._program(
            key,
            lambda: jax.jit(
                shard_map(
                    body,
                    mesh=self._mesh,
                    in_specs=P(_AXIS),
                    out_specs=P(_AXIS),
                    check_vma=False,
                )
            ),
        )
        out = prog(arr)  # global [world*world, k...]
        if not is_list:
            return out
        shards = sorted(out.addressable_shards, key=lambda s: s.index[0].start)
        return [s.data for s in shards]

    def reducescatter(
        self, tensors, opts: ReduceScatterOptions = ReduceScatterOptions()
    ):
        red_op = opts.reduceOp
        world = self._world_size
        # per-rank input is the full tensor; shape check before tracing
        if isinstance(tensors, (list, tuple)):
            dim0 = jnp.shape(tensors[0])[0]
        else:
            dim0 = tensors.shape[1]  # stacked [world, m, ...]
        if dim0 % world != 0:
            raise ValueError(
                f"reducescatter dim0 {dim0} not divisible by world_size {world}"
            )

        def body(x):  # x: [1, world*k...] per rank holds full input
            y = jax.lax.psum(x, _AXIS) if red_op in (ReduceOp.SUM, ReduceOp.AVERAGE) else _reduce_fn(red_op)(x)
            if red_op == ReduceOp.AVERAGE:
                y = y / world
            idx = jax.lax.axis_index(_AXIS)
            chunk = y.shape[1] // world
            return jax.lax.dynamic_slice_in_dim(y, idx * chunk, chunk, axis=1)

        return self._run(("reducescatter", red_op), tensors, body)

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        ones = [jnp.zeros((), jnp.int32) for _ in range(self._world_size)]
        out = self.allreduce(ones)
        jax.block_until_ready(out)

    # -- eager p2p ------------------------------------------------------
    # Single-controller semantics: send() eagerly copies the tensor onto
    # the destination rank's DEVICE (the actual D2D/ICI transfer — what
    # p2p exists for) and parks it in a per-destination FIFO mailbox;
    # recv(rank) pops the oldest tensor delivered to that rank. The
    # reference's worker-resident send/recv (collective.py:541-625) maps
    # to StoreGroup across processes; inside jitted programs use
    # lax.ppermute.
    def send(self, tensors, opts: SendOptions):
        if not hasattr(self, "_p2p_mailbox"):
            self._p2p_mailbox = {}
        tensor = tensors[0] if isinstance(tensors, (list, tuple)) else tensors
        dst_dev = self._devices[opts.dst_rank]
        delivered = jax.device_put(jnp.asarray(tensor), dst_dev)
        self._p2p_mailbox.setdefault(opts.dst_rank, []).append(delivered)

    def recv(self, tensors_or_opts=None, opts: RecvOptions = None):
        # tolerate both recv(opts) and recv(tensors, opts) call shapes
        if opts is None:
            opts = tensors_or_opts
        box = getattr(self, "_p2p_mailbox", {})
        queue = box.get(opts.src_rank)
        if not queue:
            raise RuntimeError(
                f"no pending p2p message for rank {opts.src_rank} "
                f"(single-controller group: send() must precede recv())"
            )
        return queue.pop(0)
