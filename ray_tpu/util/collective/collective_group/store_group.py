"""Store-backed collective group: eager cross-process collectives.

The CPU / cross-process fallback, playing the reference's gloo role
(python/ray/util/collective/collective_group/gloo_collective_group.py)
with the rendezvous pattern of NCCLUniqueIDStore
(nccl_collective_group.py:29-92): a *named coordinator actor* holds the
group state; each rank's eager op posts its contribution and polls for
the reduced result. Bandwidth rides the runtime's shared-memory object
plane, so one-host transfers are zero-ish copy.

Used for: heterogeneous/CPU workers, cross-process tests without
devices (the reference's CPUCommunicator test pattern,
python/ray/experimental/channel/cpu_communicator.py), and control-plane
barriers between gang workers before they enter jitted programs.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)
from .base import BaseGroup


def _np_reduce(chunks: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    stack = np.stack(chunks)
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.AVERAGE:
        return stack.mean(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.PRODUCT:
        return stack.prod(axis=0)
    raise ValueError(f"unsupported reduce op {op}")


class _Coordinator:
    """Named actor holding per-op mailboxes. One instance per group.

    Methods are tiny and non-blocking (ranks poll) so the actor's
    single-threaded queue never deadlocks.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        # (op_seq) -> {rank: payload}
        self.boxes: Dict[Tuple, Dict[int, Any]] = {}
        # key -> how many ranks already pulled the completed box
        self.pickups: Dict[Tuple, int] = {}
        # p2p mailboxes: (src, dst, tag) -> payload
        self.mail: Dict[Tuple, Any] = {}
        # rank -> number of times it joined (group incarnations)
        self.joins: Dict[int, int] = {}

    def join(self, rank: int) -> int:
        """Rank's incarnation number (1 on first join, 2 after the whole
        group is re-created, ...). Incarnations are folded into op keys,
        so a re-created group can never collect a stale box left by a
        previous incarnation that timed out or died mid-op. (If only ONE
        member re-joins a live group, its incarnation diverges and its
        ops time out — loud failure instead of silent corruption;
        rebuild the whole group in that case.)"""
        self.joins[rank] = self.joins.get(rank, 0) + 1
        return self.joins[rank]

    def post(self, key: Tuple, rank: int, payload: Any) -> None:
        self.boxes.setdefault(key, {})[rank] = payload

    def collect(self, key: Tuple) -> Optional[Dict[int, Any]]:
        """Returns the full mailbox once all ranks posted, else None."""
        box = self.boxes.get(key)
        if box is None or len(box) < self.world_size:
            return None
        # keep until all ranks pulled, then GC
        result = dict(box)
        picked = self.pickups.get(key, 0) + 1
        if picked >= self.world_size:
            self.boxes.pop(key, None)
            self.pickups.pop(key, None)
        else:
            self.pickups[key] = picked
        return result

    def p2p_send(self, src: int, dst: int, tag: int, payload: Any) -> None:
        self.mail[(src, dst, tag)] = payload

    def p2p_recv(self, src: int, dst: int, tag: int) -> Tuple[bool, Any]:
        key = (src, dst, tag)
        if key in self.mail:
            return True, self.mail.pop(key)
        return False, None


class StoreGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        import ray_tpu

        # world_size is part of the rendezvous name so a later group that
        # reuses the name with a different size can never adopt a stale
        # coordinator (whose collect() would fire at the old world count).
        actor_name = f"__collective_{group_name}_w{world_size}"
        coord_cls = ray_tpu.remote(_Coordinator)
        try:
            self._coord = ray_tpu.get_actor(actor_name)
        except ValueError:
            try:
                self._coord = coord_cls.options(
                    name=actor_name, lifetime="detached"
                ).remote(world_size)
            except Exception:
                # lost the creation race
                self._coord = ray_tpu.get_actor(actor_name)
        self._seq = 0
        self._send_tags: Dict[int, int] = {}  # dst -> next tag
        self._recv_tags: Dict[int, int] = {}  # src -> next tag
        self._ray = ray_tpu
        self._inc = ray_tpu.get(self._coord.join.remote(rank))

    @property
    def backend(self) -> str:
        return "store"

    def destroy_group(self) -> None:
        # Drop only local state. The named coordinator actor is shared by
        # all ranks — killing it here would break peers still polling an
        # in-flight op; it dies with the session (or via an explicit
        # ray_tpu.kill by the application).
        self._coord = None

    # -- plumbing ------------------------------------------------------

    def _to_np(self, t) -> np.ndarray:
        return np.asarray(t)

    def _exchange(self, op_name: str, payload, timeout_ms: int) -> Dict[int, Any]:
        key = (op_name, self._inc, self._seq)
        self._seq += 1
        self._ray.get(self._coord.post.remote(key, self._rank, payload))
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            box = self._ray.get(self._coord.collect.remote(key))
            if box is not None:
                return box
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective {op_name} timed out in group "
                    f"{self._group_name} (rank {self._rank}); "
                    f"did all {self._world_size} ranks call it?"
                )
            time.sleep(0.001)

    # -- collectives ---------------------------------------------------

    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        box = self._exchange("allreduce", self._to_np(tensor), opts.timeout_ms)
        return _np_reduce([box[r] for r in range(self._world_size)], opts.reduceOp)

    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        box = self._exchange("reduce", self._to_np(tensor), opts.timeout_ms)
        if self._rank == opts.root_rank:
            return _np_reduce(
                [box[r] for r in range(self._world_size)], opts.reduceOp
            )
        return self._to_np(tensor)

    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        payload = self._to_np(tensor) if self._rank == opts.root_rank else None
        box = self._exchange("broadcast", payload, opts.timeout_ms)
        return box[opts.root_rank]

    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        box = self._exchange("allgather", self._to_np(tensor), opts.timeout_ms)
        return np.stack([box[r] for r in range(self._world_size)])

    def reducescatter(
        self, tensor, opts: ReduceScatterOptions = ReduceScatterOptions()
    ):
        arr = self._to_np(tensor)
        if arr.shape[0] % self._world_size != 0:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by "
                f"world_size {self._world_size}"
            )
        box = self._exchange("reducescatter", arr, opts.timeout_ms)
        red = _np_reduce([box[r] for r in range(self._world_size)], opts.reduceOp)
        chunk = red.shape[0] // self._world_size
        return red[self._rank * chunk : (self._rank + 1) * chunk]

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        self._exchange("barrier", None, opts.timeout_ms)

    # -- p2p -----------------------------------------------------------

    def send(self, tensor, opts: SendOptions):
        tag = self._send_tags.get(opts.dst_rank, 0)
        self._send_tags[opts.dst_rank] = tag + 1
        self._ray.get(
            self._coord.p2p_send.remote(
                self._rank, opts.dst_rank, (self._inc, tag), self._to_np(tensor)
            )
        )

    def recv(self, opts: RecvOptions):
        tag = self._recv_tags.get(opts.src_rank, 0)
        self._recv_tags[opts.src_rank] = tag + 1
        deadline = time.monotonic() + opts.timeout_ms / 1000.0
        while True:
            ok, payload = self._ray.get(
                self._coord.p2p_recv.remote(
                    opts.src_rank, self._rank, (self._inc, tag)
                )
            )
            if ok:
                return payload
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"recv from rank {opts.src_rank} timed out "
                    f"(group {self._group_name})"
                )
            time.sleep(0.001)
