"""Abstract collective group.

Parity: python/ray/util/collective/collective_group/base_collective_group.py
(BaseGroup) and the compiled-graph Communicator ABC
(python/ray/experimental/channel/communicator.py:19) folded into one
interface: a group knows its world_size/rank and serves the full op set.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def group_name(self) -> str:
        return self._group_name

    @property
    @abstractmethod
    def backend(self) -> str: ...

    @abstractmethod
    def destroy_group(self) -> None: ...

    @abstractmethod
    def allreduce(self, tensors, opts: AllReduceOptions = AllReduceOptions()): ...

    @abstractmethod
    def barrier(self, opts: BarrierOptions = BarrierOptions()): ...

    @abstractmethod
    def reduce(self, tensors, opts: ReduceOptions = ReduceOptions()): ...

    @abstractmethod
    def broadcast(self, tensors, opts: BroadcastOptions = BroadcastOptions()): ...

    @abstractmethod
    def allgather(self, tensors, opts: AllGatherOptions = AllGatherOptions()): ...

    @abstractmethod
    def reducescatter(
        self, tensors, opts: ReduceScatterOptions = ReduceScatterOptions()
    ): ...

    @abstractmethod
    def send(self, tensors, opts: SendOptions): ...

    @abstractmethod
    def recv(self, tensors, opts: RecvOptions): ...
