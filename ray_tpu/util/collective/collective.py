"""Public collective API.

Parity: python/ray/util/collective/collective.py in the reference —
init_collective_group (:123), create_collective_group (:160, declarative
form), allreduce (:268), barrier (:308), reduce/broadcast/allgather/
reducescatter (:321-512), send/recv (:541-625), GroupManager (:40).

TPU-native semantics: backend "xla" groups are in-process device meshes
(collectives = cached jitted XLA programs riding ICI); backend "store"
groups are cross-process, rendezvoused through a named coordinator
actor (the NCCLUniqueIDStore pattern).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from .types import (
    AllGatherOptions,
    AllReduceOptions,
    Backend,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


class GroupManager:
    """Per-process registry of collective groups (reference :40)."""

    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create_group(
        self,
        backend: str,
        world_size: int,
        rank: int,
        group_name: str,
        **kwargs,
    ):
        backend = Backend(backend)
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(f"group {group_name!r} already initialized")
            if backend == Backend.XLA:
                from .collective_group.xla_group import XlaGroup

                group = XlaGroup(world_size, rank, group_name, **kwargs)
            else:
                from .collective_group.store_group import StoreGroup

                group = StoreGroup(world_size, rank, group_name)
            self._groups[group_name] = group
            return group

    def get_group(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"collective group {group_name!r} is not initialized in this "
                "process; call init_collective_group first"
            )
        return group

    def is_group_initialized(self, group_name: str) -> bool:
        return group_name in self._groups

    def destroy_group(self, group_name: str) -> None:
        with self._lock:
            group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy_group()


_group_mgr = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "xla",
    group_name: str = "default",
    **kwargs,
):
    """Initialize this process's membership in a collective group
    (reference :123). For backend='xla' with world_size == local device
    count, rank is a formality (single-controller owns all devices)."""
    return _group_mgr.create_group(backend, world_size, rank, group_name, **kwargs)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = "store",
    group_name: str = "default",
):
    """Declarative form (reference :160): the driver initializes a group
    over existing actors. Each actor must expose an
    ``init_collective_group``-calling method or be a plain actor — we
    invoke the module API inside each via a closure task."""
    import ray_tpu

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must align")

    def _init_in_actor(actor, rank):
        return actor.__ray_call__.remote(
            lambda self, ws=world_size, r=rank, b=backend, g=group_name: (
                init_collective_group(ws, r, backend=b, group_name=g)
                and None
            )
        )

    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(_init_in_actor(actor, rank))
    ray_tpu.get(refs)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.is_group_initialized(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    _group_mgr.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


def get_group_handle(group_name: str = "default"):
    return _group_mgr.get_group(group_name)


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).allreduce(
        tensor, AllReduceOptions(reduceOp=op)
    )


def reduce(
    tensor,
    dst_rank: int = 0,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
):
    return _group_mgr.get_group(group_name).reduce(
        tensor, ReduceOptions(reduceOp=op, root_rank=dst_rank)
    )


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get_group(group_name).broadcast(
        tensor, BroadcastOptions(root_rank=src_rank)
    )


def allgather(tensor, group_name: str = "default"):
    return _group_mgr.get_group(group_name).allgather(tensor, AllGatherOptions())


def reducescatter(
    tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM
):
    return _group_mgr.get_group(group_name).reducescatter(
        tensor, ReduceScatterOptions(reduceOp=op)
    )


def barrier(group_name: str = "default"):
    return _group_mgr.get_group(group_name).barrier(BarrierOptions())


def send(tensor, dst_rank: int, group_name: str = "default"):
    return _group_mgr.get_group(group_name).send(
        tensor, SendOptions(dst_rank=dst_rank)
    )


def recv(src_rank: int, group_name: str = "default"):
    return _group_mgr.get_group(group_name).recv(RecvOptions(src_rank=src_rank))
