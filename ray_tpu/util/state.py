"""State API: list + summarize cluster entities.

Parity: python/ray/util/state/api.py (:784 list_*, :1359-1425
summarize_*) over the hub's live tables instead of a dashboard
aggregator head.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def list_actors(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("actors"), filters)

def list_tasks(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("tasks"), filters)

def list_workers(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("workers"), filters)

def list_nodes(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("nodes"), filters)

def list_objects(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("objects"), filters)

def list_placement_groups(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("placement_groups"), filters)


def _apply_filters(items: List[dict], filters: Optional[list]) -> List[dict]:
    """filters: [(key, "=" | "!=", value), ...] (reference filter shape)."""
    if not filters:
        return items
    out = []
    for item in items:
        ok = True
        for key, op, value in filters:
            got = item.get(key)
            if op == "=" and got != value:
                ok = False
            elif op == "!=" and got == value:
                ok = False
        if ok:
            out.append(item)
    return out


def summarize_tasks() -> Dict[str, Any]:
    """Counts by state and by function (reference: summarize_tasks)."""
    events = _client().list_state("tasks")
    by_state = Counter(e.get("state", "UNKNOWN") for e in events)
    by_func: Dict[str, Counter] = {}
    for e in events:
        name = (e.get("name") or "unknown").split(":")[0]
        by_func.setdefault(name, Counter())[e.get("state", "UNKNOWN")] += 1
    return {
        "total": len(events),
        "by_state": dict(by_state),
        "by_func_name": {k: dict(v) for k, v in by_func.items()},
    }


def summarize_actors() -> Dict[str, Any]:
    actors = _client().list_state("actors")
    return {
        "total": len(actors),
        "by_state": dict(Counter(a["state"] for a in actors)),
    }


def summarize_objects() -> Dict[str, Any]:
    objects = _client().list_state("objects")
    ready = [o for o in objects if o.get("ready")]
    return {
        "total": len(objects),
        "ready": len(ready),
        "total_size_bytes": sum(o.get("size", 0) for o in ready),
    }
