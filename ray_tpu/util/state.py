"""State API: list + summarize cluster entities.

Parity: python/ray/util/state/api.py (:784 list_*, :1359-1425
summarize_*) over the hub's live tables instead of a dashboard
aggregator head.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def list_actors(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("actors"), filters)

def list_tasks(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("tasks"), filters)

def list_workers(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("workers"), filters)

def list_nodes(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("nodes"), filters)

def list_objects(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("objects"), filters)

def list_placement_groups(filters: Optional[list] = None) -> List[dict]:
    return _apply_filters(_client().list_state("placement_groups"), filters)

def list_events(filters: Optional[list] = None) -> List[dict]:
    """Flight-recorder runtime events (node up/down, worker exits,
    retries, spills, ...) — the hub's bounded post-mortem log."""
    return _apply_filters(_client().list_state("events"), filters)

def list_jobs(filters: Optional[list] = None) -> List[dict]:
    """Registered scheduler jobs (fairsched): tenant, priority, quota,
    submit/dispatch/preemption counters. Distinct from the entrypoint
    job table (job_submission.JobSubmissionClient.list_jobs)."""
    return _apply_filters(_client().list_state("jobs"), filters)

def list_shards(filters: Optional[list] = None) -> List[dict]:
    """Control-plane topology: one row per reactor shard (conns,
    wakeups, frames sent) plus one per state service (messages
    processed). A single-reactor hub reports its one implicit shard
    (hub_shards.py; RAY_TPU_HUB_SHARDS)."""
    return _apply_filters(_client().list_state("shards"), filters)


def list_tenants(filters: Optional[list] = None) -> List[dict]:
    """Per-tenant scheduling accounting: quota vs admitted usage,
    fair-share clock, share of running work, pending_quota depth."""
    return _apply_filters(_client().list_state("tenants"), filters)


def list_chaos(filters: Optional[list] = None) -> List[dict]:
    """Fault-injection plane (chaos.py): the active RAY_TPU_CHAOS_PLAN
    with per-fault trigger counts (first row, present only when a plan
    is set), then recent fault events from the flight recorder —
    chaos_* kinds, plus the recovery events task_timeout and
    node_heartbeat_miss, which appear whether or not the fault was
    injected (a real hang or partition lands here too)."""
    return _apply_filters(_client().list_state("chaos"), filters)


def list_traces(filters: Optional[list] = None) -> List[dict]:
    """Sampled distributed traces (util/tracing.py runtime spans): one
    summary row per trace_id — span count, start, duration, root span
    name, number of distinct processes. Use get_trace() for spans."""
    return _apply_filters(_client().list_state("traces"), filters)


def list_serve(filters: Optional[list] = None) -> List[dict]:
    """Serve-plane SLO rows: one per (deployment, route) pivoted from
    the builtin metric registry — request/error/timeout counters,
    latency + batch histograms ({sum, count, buckets}), live load
    gauges. summarize_serve() turns these into percentiles."""
    return _apply_filters(_client().list_state("serve"), filters)


def list_profile(filters: Optional[list] = None) -> List[dict]:
    """Folded profiler samples aggregated at the hub (profiling.py):
    one row per distinct (pid, process kind, thread domain, stage,
    task, collapsed stack) with its sample count, plus one meta row per
    reporting process (proc=True: kind, hz, self-overhead ratio).
    Empty unless RAY_TPU_PROFILE_HZ > 0 somewhere in the cluster."""
    return _apply_filters(_client().list_state("profile"), filters)


def get_trace(trace_id: str) -> List[dict]:
    """All recorded spans of one trace, raw (feed these through
    ray_tpu.util.tracing.analyze_trace for the critical-path view)."""
    return _client().list_state("traces", trace_id=trace_id)


def summarize_trace(trace_id: str) -> Dict[str, Any]:
    """Critical-path breakdown of one trace: per-stage durations,
    dominant stage, untracked remainder (util/tracing.analyze_trace)."""
    from ray_tpu.util.tracing import analyze_trace

    return analyze_trace(get_trace(trace_id))


def _apply_filters(items: List[dict], filters: Optional[list]) -> List[dict]:
    """filters: [(key, "=" | "!=", value), ...] (reference filter shape)."""
    if not filters:
        return items
    out = []
    for item in items:
        ok = True
        for key, op, value in filters:
            got = item.get(key)
            if op == "=" and got != value:
                ok = False
            elif op == "!=" and got == value:
                ok = False
        if ok:
            out.append(item)
    return out


def _percentiles(values: List[float]) -> Optional[Dict[str, float]]:
    """Nearest-rank p50/p95/p99 — small-n friendly, no numpy needed."""
    if not values:
        return None
    vs = sorted(values)

    def rank(p: float) -> float:
        return vs[min(len(vs) - 1, int(round(p / 100.0 * (len(vs) - 1))))]

    return {"p50": rank(50), "p95": rank(95), "p99": rank(99),
            "max": vs[-1], "count": len(vs)}


def summarize_tasks() -> Dict[str, Any]:
    """Counts by state and by function, plus the lifecycle latency
    breakdown (reference: summarize_tasks): queue-wait is submit ->
    dispatch-to-worker, run-time is dispatch -> done, both computed
    from the hub's monotonic t_* stamps."""
    events = _client().list_state("tasks")
    by_state = Counter(e.get("state", "UNKNOWN") for e in events)
    by_func: Dict[str, Counter] = {}
    queue_waits: List[float] = []
    run_times: List[float] = []
    for e in events:
        name = (e.get("name") or "unknown").split(":")[0]
        by_func.setdefault(name, Counter())[e.get("state", "UNKNOWN")] += 1
        # queue wait starts at the LATEST queue entry (retries re-stamp
        # t_queued; actor calls have no queued phase and fall back to
        # t_submit) so the breakdown reflects the final attempt
        t0 = e.get("t_queued") or e.get("t_submit")
        t_sched, t_fin = e.get("t_scheduled"), e.get("t_finished")
        if t0 is not None and t_sched is not None:
            queue_waits.append(max(0.0, t_sched - t0))
        if t_sched is not None and t_fin is not None:
            run_times.append(max(0.0, t_fin - t_sched))
    return {
        "total": len(events),
        "by_state": dict(by_state),
        "by_func_name": {k: dict(v) for k, v in by_func.items()},
        "queue_wait_s": _percentiles(queue_waits),
        "run_time_s": _percentiles(run_times),
    }


def _hist_percentile(buckets: List[list], count: int, p: float) -> Optional[float]:
    """Percentile estimate from histogram buckets: the upper bound of
    the bucket where the cumulative count crosses p% of observations
    (Prometheus histogram_quantile style, upper-bound conservative).
    Observations above the largest boundary report that boundary."""
    if not count or not buckets:
        return None
    target = p / 100.0 * count
    cum = 0
    for bound, c in buckets:
        cum += c
        if cum >= target:
            return bound
    return buckets[-1][0]


def summarize_serve() -> Dict[str, Any]:
    """Per-deployment serve SLO summary: request/error/timeout counts
    and latency p50/p95/p99 per route (estimated from histogram
    buckets), live load gauges (ongoing/queued/replicas), drain-vs-drop
    teardown counters, and batch efficiency (mean actual/max batch
    size, 1.0 = every batch full)."""
    deployments: Dict[str, Any] = {}
    for row in list_serve():
        dep = deployments.setdefault(row["deployment"], {
            "requests": 0, "errors": 0, "timeouts": 0,
            "ongoing": 0, "queued": 0, "replicas": 0,
            "drained": 0, "dropped": 0, "model_swaps": 0,
            "shed": 0, "expired": 0, "ejections": 0,
            "batch_efficiency": None,
            "routes": {},
        })
        rstats: Dict[str, Any] = {
            "requests": int(row.get("requests_total", 0)),
            "errors": int(row.get("errors_total", 0)),
            "timeouts": int(row.get("timeouts_total", 0)),
            "latency_s": None,
        }
        lat = row.get("request_latency_seconds")
        if lat and lat["count"]:
            rstats["latency_s"] = {
                "p50": _hist_percentile(lat["buckets"], lat["count"], 50),
                "p95": _hist_percentile(lat["buckets"], lat["count"], 95),
                "p99": _hist_percentile(lat["buckets"], lat["count"], 99),
                "mean": lat["sum"] / lat["count"],
                "count": lat["count"],
            }
        dep["routes"][row["route"]] = rstats
        dep["requests"] += rstats["requests"]
        dep["errors"] += rstats["errors"]
        dep["timeouts"] += rstats["timeouts"]
        # per-deployment series (gauges, batch + teardown counters) are
        # recorded without a route tag and so ride the route="" row
        if "ongoing_requests" in row:
            dep["ongoing"] = int(row["ongoing_requests"])
        if "queue_depth" in row:
            dep["queued"] = int(row["queue_depth"])
        if "replicas" in row:
            dep["replicas"] = int(row["replicas"])
        dep["drained"] += int(row.get("drained_requests_total", 0))
        dep["dropped"] += int(row.get("dropped_requests_total", 0))
        dep["model_swaps"] += int(row.get("model_swaps_total", 0))
        # overload/resilience counters: shed (admission refusals) and
        # expired (deadline drops) are disjoint from drained/dropped —
        # shed requests were never admitted, expired ones never ran
        dep["shed"] += int(row.get("shed_total", 0))
        dep["expired"] += int(row.get("expired_requests_total", 0))
        dep["ejections"] += int(row.get("ejections_total", 0))
        ratio = row.get("batch_ratio")
        if ratio and ratio["count"]:
            dep["batch_efficiency"] = ratio["sum"] / ratio["count"]
    return {"deployments": deployments}


def summarize_actors() -> Dict[str, Any]:
    actors = _client().list_state("actors")
    return {
        "total": len(actors),
        "by_state": dict(Counter(a["state"] for a in actors)),
    }


def leak_suspects(min_age_s: float = 60.0,
                  objects: Optional[List[dict]] = None) -> List[dict]:
    """Ready objects that look leaked: their owning process is gone
    (nothing can ever release the ref), no in-flight task pins them,
    and they have been alive at least min_age_s. Backs
    `ray_tpu memory --leak-suspects`."""
    if objects is None:
        objects = _client().list_state("objects")
    return [
        o for o in objects
        if o.get("ready")
        and not o.get("owner_alive", True)
        and not o.get("pins", 0)
        and o.get("age_s", 0.0) >= min_age_s
    ]


def summarize_objects() -> Dict[str, Any]:
    objects = _client().list_state("objects")
    ready = [o for o in objects if o.get("ready")]
    by_owner: Dict[str, Dict[str, Any]] = {}
    for o in ready:
        ow = by_owner.setdefault(o.get("owner") or "?", {
            "count": 0, "size_bytes": 0,
        })
        ow["count"] += 1
        ow["size_bytes"] += o.get("size", 0)
    return {
        "total": len(objects),
        "ready": len(ready),
        "total_size_bytes": sum(o.get("size", 0) for o in ready),
        "spilled": sum(1 for o in ready if o.get("spilled")),
        "by_owner": by_owner,
        "leak_suspects": len(leak_suspects(objects=objects)),
    }
