"""joblib backend: run scikit-learn's `n_jobs` parallelism on the
cluster.

Parity: python/ray/util/joblib/ (`register_ray` + the ray joblib
backend over the multiprocessing-Pool API). Here each joblib batch
(a zero-arg BatchedCalls closure) ships as one task; callbacks fire
from a small watcher thread per in-flight batch, matching the
multiprocessing.Pool callback contract joblib expects.

    from ray_tpu.util.joblib import register_ray
    import joblib

    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        GridSearchCV(...).fit(X, y)   # n_jobs=-1 fans out as tasks
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional


def _run_batch(batch: Callable) -> Any:
    return batch()


class _RayAsyncResult:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        return ray_tpu.get(self._ref, timeout=timeout)


def _get_remote():
    # no module-level cache: a cached RemoteFunction would outlive
    # ray_tpu.shutdown()/init() cycles and submit into a dead client
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    return ray_tpu.remote(_run_batch)


def register_ray() -> None:
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib.parallel import ParallelBackendBase, register_parallel_backend

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, **backend_args):
            import ray_tpu

            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if n_jobs is None:
                return 1
            if n_jobs < 0:
                if not ray_tpu.is_initialized():
                    ray_tpu.init(ignore_reinit_error=True)
                return max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
            return n_jobs

        def apply_async(self, func, callback=None):
            ref = _get_remote().remote(func)
            result = _RayAsyncResult(ref)
            if callback is not None:
                # multiprocessing.Pool contract: callback(result_value)
                # from a helper thread once the task completes
                def _watch():
                    try:
                        value = result.get()
                    except Exception:
                        return  # error surfaces via .get() in retrieval
                    callback(value)

                threading.Thread(target=_watch, daemon=True).start()
            return result

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(
                    n_jobs=self.parallel.n_jobs, parallel=self.parallel
                )

    register_parallel_backend("ray_tpu", RayTpuBackend)


__all__ = ["register_ray"]
