"""Top-k checkpoint retention.

Parity: python/ray/train/_internal/checkpoint_manager.py (register
reported checkpoints, keep num_to_keep best by score attribute, delete
the rest from storage).
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ...air.config import CheckpointConfig
from .._checkpoint import Checkpoint


@dataclass
class _Tracked:
    checkpoint: Checkpoint
    metrics: Dict[str, Any]
    index: int


class CheckpointManager:
    def __init__(self, config: CheckpointConfig):
        self.config = config
        self._tracked: List[_Tracked] = []
        self._count = 0

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> None:
        self._tracked.append(_Tracked(checkpoint, dict(metrics), self._count))
        self._count += 1
        self._enforce()

    def _score(self, t: _Tracked) -> Tuple:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return (t.index,)  # recency
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        val = t.metrics.get(attr)
        if val is None:
            return (float("-inf"), t.index)
        return (sign * float(val), t.index)

    def _enforce(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self._tracked) <= k:
            return
        self._tracked.sort(key=self._score, reverse=True)
        keep, drop = self._tracked[:k], self._tracked[k:]
        # never delete the most recent checkpoint — it's the resume point
        latest = max(self._tracked, key=lambda t: t.index)
        if latest in drop:
            drop.remove(latest)
            if keep:
                worst = min(keep, key=self._score)
                keep.remove(worst)
                drop.append(worst)
            keep.append(latest)
        for t in drop:
            if os.path.isdir(t.checkpoint.path):
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._tracked = sorted(keep, key=lambda t: t.index)

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=lambda t: t.index).checkpoint

    def best_checkpoint(
        self, metric: Optional[str] = None, mode: str = "max"
    ) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        attr = metric or self.config.checkpoint_score_attribute
        if attr is None:
            return self.latest_checkpoint
        sign = 1.0 if mode == "max" else -1.0
        scored = [t for t in self._tracked if attr in t.metrics]
        if not scored:
            return self.latest_checkpoint
        return max(scored, key=lambda t: sign * float(t.metrics[attr])).checkpoint

    @property
    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        return [(t.checkpoint, t.metrics) for t in self._tracked]
