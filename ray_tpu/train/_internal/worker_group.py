"""WorkerGroup: a gang of TrainWorker actors in one placement group.

Parity: python/ray/train/_internal/worker_group.py:102 (WorkerGroup of
RayTrainWorker actors) + backend_executor.py:230 (PACK placement group)
+ :363 (rank sorting). TPU-native: the gang is all-or-nothing (a slice
runs one SPMD program); workers are sorted by (node, chip ids) so rank
0 is the coordinator host and mesh coordinates line up with ICI
neighbors.
"""

from __future__ import annotations

import queue
import threading
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ...air.config import ScalingConfig


class GangUnschedulableError(RuntimeError):
    """The worker gang cannot currently be placed (elastic trainers
    react by shrinking; reference: v2 scaling_policy resize decisions)."""


class TrainWorker:
    """Actor hosting one training process's session + train_fn thread."""

    def __init__(self, world_size: int, experiment_name: str):
        self.rank = -1  # assigned at setup_session, after topology sort
        self.world_size = world_size
        self.experiment_name = experiment_name
        self.session = None
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[str] = None
        self.finished = False
        self.final_result: Any = None

    def get_metadata(self) -> Dict[str, Any]:
        import os
        import socket

        return {
            "pid": os.getpid(),
            # node agents export the (possibly simulated) host identity;
            # node_ip is what rank-0 peers can actually dial for the
            # jax.distributed coordinator
            "hostname": os.environ.get("RAY_TPU_NODE_HOSTNAME")
            or socket.gethostname(),
            "node_ip": os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1"),
            "tpu_chips": os.environ.get("TPU_VISIBLE_CHIPS", ""),
        }

    def pick_free_port(self) -> int:
        """Bind-probe a free port (runs on rank 0's host; the coordinator
        binds it immediately after, same pattern as the reference's
        get_address_and_port, train/torch/config.py:66)."""
        import socket

        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    def setup_session(
        self,
        rank: int,
        storage_dir: str,
        latest_checkpoint_path: Optional[str],
        dataset_shards: Optional[Dict[str, Any]] = None,
        start_iteration: int = 0,
        sync_reports: bool = False,
        local_rank: Optional[int] = None,
        local_world_size: Optional[int] = None,
        node_rank: int = 0,
    ) -> None:
        from .._checkpoint import Checkpoint
        from ..session import TrainContext, _TrainSession, _init_session

        self.rank = rank
        ctx = TrainContext(
            world_size=self.world_size,
            world_rank=self.rank,
            local_rank=self.rank if local_rank is None else local_rank,
            local_world_size=(
                self.world_size if local_world_size is None else local_world_size
            ),
            node_rank=node_rank,
            experiment_name=self.experiment_name,
        )
        ckpt = (
            Checkpoint(latest_checkpoint_path) if latest_checkpoint_path else None
        )
        self.session = _TrainSession(
            ctx,
            storage_dir,
            latest_checkpoint=ckpt,
            dataset_shards=dataset_shards,
            start_iteration=start_iteration,
            sync_reports=sync_reports,
        )
        _init_session(self.session)

    def run_backend_hook(self, hook_fn: Callable, *args) -> Any:
        """Run a Backend.on_start/on_shutdown-style callable in-process."""
        return hook_fn(self, *args)

    def start_training(self, train_fn: Callable, config: Dict[str, Any]) -> None:
        if self.session is None:
            raise RuntimeError("setup_session must run before start_training")
        self.finished = False
        self.error = None

        def run():
            try:
                import inspect

                sig = inspect.signature(train_fn)
                self.final_result = (
                    train_fn(config) if len(sig.parameters) >= 1 else train_fn()
                )
            except StopIteration:
                pass  # controller-requested stop
            except Exception:
                self.error = traceback.format_exc()
            finally:
                self.finished = True

        self.thread = threading.Thread(target=run, daemon=True, name="train_fn")
        self.thread.start()

    def poll(self) -> Dict[str, Any]:
        """Drain queued report() results; controller calls this in a loop
        (reference: backend_executor.get_next_results :588)."""
        results = []
        while True:
            try:
                results.append(self.session.result_queue.get_nowait())
            except queue.Empty:
                break
        return {
            "results": results,
            "finished": self.finished,
            "error": self.error,
            "final_result": self.final_result if self.finished else None,
        }

    def request_stop(self) -> None:
        if self.session:
            self.session.stop_requested.set()

    def shutdown_session(self) -> None:
        from ..session import _shutdown_session

        _shutdown_session()


@dataclass
class WorkerHandle:
    actor: Any
    rank: int
    metadata: Dict[str, Any]


class WorkerGroup:
    """Creates/destroys the actor gang (reference worker_group.py:102)."""

    def __init__(self, scaling_config: ScalingConfig, experiment_name: str):
        self.scaling_config = scaling_config
        self.experiment_name = experiment_name
        self.workers: List[WorkerHandle] = []
        self._pg = None

    def start(self) -> None:
        import ray_tpu
        from ...util.placement_group import placement_group

        sc = self.scaling_config
        bundles = [
            sc._resources_per_worker_not_none() for _ in range(sc.num_workers)
        ]
        self._pg = placement_group(bundles, strategy=sc.placement_strategy)
        if not self._pg.wait(timeout_seconds=sc.placement_timeout_s):
            from ...util.placement_group import remove_placement_group

            remove_placement_group(self._pg)
            raise GangUnschedulableError(
                f"placement group for {sc.num_workers} train workers "
                f"({bundles[0]} each) not schedulable on this cluster"
            )
        from ...util.scheduling_strategies import PlacementGroupSchedulingStrategy

        worker_cls = ray_tpu.remote(TrainWorker)
        res = sc._resources_per_worker_not_none()
        opts: Dict[str, Any] = {"num_cpus": res.get("CPU", 1)}
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        extra = {
            k: v for k, v in res.items() if k not in ("CPU", "TPU", "GPU", "memory")
        }
        if extra:
            opts["resources"] = extra
        actors = [
            worker_cls.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg, placement_group_bundle_index=i
                ),
                **opts,
            ).remote(sc.num_workers, self.experiment_name)
            for i in range(sc.num_workers)
        ]
        metas = ray_tpu.get([a.get_metadata.remote() for a in actors])
        # sort by (hostname, chip ids) so ranks match physical adjacency
        order = sorted(
            range(len(actors)),
            key=lambda i: (metas[i]["hostname"], metas[i]["tpu_chips"]),
        )
        self.workers = [
            WorkerHandle(actor=actors[j], rank=new_rank, metadata=metas[j])
            for new_rank, j in enumerate(order)
        ]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call a TrainWorker method on every worker, gather results."""
        import ray_tpu

        refs = [
            getattr(w.actor, method).remote(*args, **kwargs) for w in self.workers
        ]
        return ray_tpu.get(refs)

    def execute_async(self, method: str, *args, **kwargs) -> List[Any]:
        return [
            getattr(w.actor, method).remote(*args, **kwargs) for w in self.workers
        ]

    def shutdown(self) -> None:
        import ray_tpu
        from ...util.placement_group import remove_placement_group

        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def __len__(self) -> int:
        return len(self.workers)
