"""TorchTrainer: torch.distributed (gloo) data-parallel training.

Parity: python/ray/train/torch/torch_trainer.py + config.py:36,153
(_TorchBackend — pick worker-0 addr/port, dist.init_process_group on
every worker). On this framework torch is the CPU-side companion to
the JAX/TPU path: the gang is the same placement-group worker group the
JaxTrainer uses; only the rendezvous differs (torch needs a process
group even for a single-host gang, since every rank is its own
process — unlike JAX's single-controller model).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ..air.config import RunConfig, ScalingConfig
from ._checkpoint import Checkpoint
from .backend import Backend, BackendConfig, rank0_rendezvous_addr
from .data_parallel_trainer import DataParallelTrainer


@dataclass
class TorchConfig(BackendConfig):
    """reference: train/torch/config.py TorchConfig (backend/timeout)."""

    backend: str = "gloo"  # CPU collectives; nccl has no TPU meaning
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return _TorchBackend


def _torch_worker_setup(worker, addr: str, world_size: int, rank: int,
                        backend: str, timeout_s: float):
    """Runs inside each TrainWorker actor (the reference's
    _setup_torch_process_group, config.py:66)."""
    import datetime

    import torch.distributed as dist

    if not dist.is_initialized():
        dist.init_process_group(
            backend,
            init_method=f"tcp://{addr}",
            world_size=world_size,
            rank=rank,
            timeout=datetime.timedelta(seconds=timeout_s),
        )
    return True


def _torch_worker_teardown(worker):
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig) -> None:
        # group inits even for n == 1: world-size-agnostic loops call
        # dist.get_world_size()/all_reduce unconditionally (reference
        # behavior — _TorchBackend always sets up the process group)
        n = len(worker_group.workers)
        import ray_tpu

        addr = rank0_rendezvous_addr(worker_group)
        ray_tpu.get([
            w.actor.run_backend_hook.remote(
                _torch_worker_setup, addr, n, w.rank,
                backend_config.backend, backend_config.init_timeout_s,
            )
            for w in worker_group.workers
        ])

    def on_shutdown(self, worker_group, backend_config: TorchConfig) -> None:
        import ray_tpu

        try:
            ray_tpu.get([
                w.actor.run_backend_hook.remote(_torch_worker_teardown)
                for w in worker_group.workers
            ])
        except Exception:
            pass  # workers may already be dead (gang teardown)


class TorchTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        torch_config: Optional[TorchConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
            metadata=metadata,
        )


def prepare_model(model):
    """Wrap in DDP when a process group is live (reference:
    ray.train.torch.prepare_model, minus device movement — CPU gloo)."""
    import torch.distributed as dist

    if dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model
