"""ray_tpu.train — distributed training on TPU gangs.

Parity: python/ray/train/ (v2 controller shape). Public surface:
JaxTrainer / DataParallelTrainer, report/get_context/get_checkpoint/
get_dataset_shard, Checkpoint, ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig, Result, Backend/BackendConfig/JaxConfig.
"""

from ..air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ..air.result import Result
from ._checkpoint import Checkpoint
from .backend import Backend, BackendConfig, JaxConfig
from .data_parallel_trainer import DataParallelTrainer, TrainingFailedError
from .jax_trainer import JaxTrainer
from .torch_trainer import TorchConfig, TorchTrainer
from .session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)

__all__ = [
    "Backend",
    "BackendConfig",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "TorchConfig",
    "TorchTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "TrainingFailedError",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("train")
