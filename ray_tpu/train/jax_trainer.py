"""JaxTrainer: the flagship trainer (reference analogue: TorchTrainer,
python/ray/train/torch/torch_trainer.py — but jit/pjit-first).

The train_loop_per_worker runs inside each gang worker with:
- ``ray_tpu.train.get_context()`` — rank/world info
- ``ray_tpu.train.report(metrics, checkpoint=...)`` — metrics + ckpt
- ``ray_tpu.train.get_checkpoint()`` — resume point after restarts
- ``ray_tpu.parallel.make_mesh(...)`` — the worker's device mesh; on a
  TPU host the single worker owns all local chips, so data/fsdp/model
  shardings compile to ICI collectives with zero framework overhead.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ..air.config import RunConfig, ScalingConfig
from ._checkpoint import Checkpoint
from .backend import JaxConfig
from .data_parallel_trainer import DataParallelTrainer


class JaxTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        jax_config: Optional[JaxConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
            metadata=metadata,
        )
