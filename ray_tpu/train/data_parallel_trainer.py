"""DataParallelTrainer + TrainController: the Train-v2 control loop.

Parity: python/ray/train/data_parallel_trainer.py (v1 user API) driven
by the v2-style controller (train/v2/_internal/execution/controller/
controller.py:91): poll the worker group, surface results, consult the
FailurePolicy on worker death, restart the gang from the latest
checkpoint. TPU-native: the gang is all-or-nothing — any worker failure
tears down and re-forms the whole group (a slice runs one SPMD
program; partial worlds are useless).
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ..air.result import Result
from ._checkpoint import Checkpoint
from ._internal.checkpoint_manager import CheckpointManager
from ._internal.worker_group import WorkerGroup
from .backend import Backend, BackendConfig

_POLL_INTERVAL_S = 0.05


class TrainingFailedError(RuntimeError):
    """Raised when training fails beyond FailureConfig.max_failures
    (parity: ray.train.base_trainer.TrainingFailedError)."""


class DataParallelTrainer:
    """Launch ``train_loop_per_worker`` on a gang of workers.

    Usage parity with the reference:
        trainer = DataParallelTrainer(
            train_loop_per_worker=fn,
            scaling_config=ScalingConfig(num_workers=4, use_tpu=True),
            run_config=RunConfig(name="exp"),
        )
        result = trainer.fit()
    """

    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self._default_backend_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self.metadata = metadata or {}
        self._callbacks = list(self.run_config.callbacks or [])

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)

        name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        storage_dir = os.path.join(
            os.path.expanduser(self.run_config.storage_path), name
        )
        os.makedirs(storage_dir, exist_ok=True)

        ckpt_mgr = CheckpointManager(self.run_config.checkpoint_config)
        failure_config = self.run_config.failure_config
        latest_ckpt = self.resume_from_checkpoint
        failures = 0
        # survives failed attempts so Result carries the last reported
        # metrics even when fit() ends in error
        self._last_metrics: Optional[Dict[str, Any]] = None
        self._next_iteration = 0
        error: Optional[Exception] = None

        from ._internal.worker_group import GangUnschedulableError

        sc = self.scaling_config
        current_workers = sc.num_workers
        while True:
            try:
                self._run_attempt(
                    name, storage_dir, ckpt_mgr, latest_ckpt,
                    num_workers=current_workers,
                )
                break
            except (TrainingFailedError, GangUnschedulableError) as e:
                failures += 1
                latest_ckpt = ckpt_mgr.latest_checkpoint or latest_ckpt
                allowed = (
                    failure_config.max_failures == -1
                    or failures <= failure_config.max_failures
                )
                if failure_config.fail_fast or not allowed:
                    error = (
                        e if isinstance(e, TrainingFailedError)
                        else TrainingFailedError(str(e))
                    )
                    break
                if (
                    isinstance(e, GangUnschedulableError)
                    and sc.min_workers
                    and current_workers > sc.min_workers
                ):
                    # elastic resize (reference: v2 ScalingPolicy): the
                    # full gang no longer fits — halve toward the floor
                    # and resume from the latest checkpoint
                    current_workers = max(sc.min_workers, current_workers // 2)
                # else: gang restart at the same size from the checkpoint

        checkpoint = ckpt_mgr.latest_checkpoint
        return Result(
            metrics=self._last_metrics,
            checkpoint=checkpoint,
            error=error,
            path=storage_dir,
            best_checkpoints=ckpt_mgr.best_checkpoints,
        )

    # ------------------------------------------------------------------
    def _run_attempt(
        self,
        name: str,
        storage_dir: str,
        ckpt_mgr: CheckpointManager,
        latest_ckpt: Optional[Checkpoint],
        num_workers: Optional[int] = None,
    ) -> None:
        import ray_tpu
        from ..exceptions import ActorError, TaskError

        sc = self.scaling_config
        if num_workers is not None and num_workers != sc.num_workers:
            import dataclasses

            sc = dataclasses.replace(sc, num_workers=num_workers)
        wg = WorkerGroup(sc, name)
        backend: Backend = self.backend_config.backend_cls()
        try:
            wg.start()
            backend.on_start(wg, self.backend_config)
            # per-worker dataset shards (streaming split)
            shards_per_worker = self._split_datasets(len(wg))
            # node-aware ranks: workers are sorted by hostname, so local
            # ranks are positions within each host's contiguous span
            hosts: list = []
            local_ranks = []
            local_sizes: dict = {}
            for w in wg.workers:
                h = w.metadata["hostname"]
                if not hosts or hosts[-1] != h:
                    hosts.append(h)
                local_ranks.append(local_sizes.get(h, 0))
                local_sizes[h] = local_ranks[-1] + 1
            refs = []
            for i, w in enumerate(wg.workers):
                h = w.metadata["hostname"]
                refs.append(
                    w.actor.setup_session.remote(
                        w.rank,
                        storage_dir,
                        latest_ckpt.path if latest_ckpt else None,
                        shards_per_worker[i],
                        self._next_iteration,
                        local_rank=local_ranks[i],
                        local_world_size=local_sizes[h],
                        node_rank=hosts.index(h),
                    )
                )
            ray_tpu.get(refs)
            backend.on_training_start(wg, self.backend_config)
            wg.execute(
                "start_training", self.train_loop_per_worker, self.train_loop_config
            )
            self._control_loop(wg, ckpt_mgr)
        except (ActorError, TaskError, ConnectionError) as e:
            raise TrainingFailedError(str(e)) from e
        finally:
            try:
                backend.on_shutdown(wg, self.backend_config)
            except Exception:
                pass
            wg.shutdown()

    def _split_datasets(self, n: int) -> List[Optional[Dict[str, Any]]]:
        if not self.datasets:
            return [None] * n
        out: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for key, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                splits = ds.streaming_split(n)
            elif hasattr(ds, "split"):
                splits = ds.split(n)
            else:
                splits = [ds] * n  # replicate non-Dataset iterables
            for i in range(n):
                out[i][key] = splits[i]
        return out

    def _control_loop(self, wg: WorkerGroup, ckpt_mgr: CheckpointManager) -> None:
        """Drain report()s until every worker's train_fn returns
        (reference: controller.py:91 control loop + backend_executor
        get_next_results :588 — results consumed iteration-aligned).

        An iteration is processed once, when reports from ALL ranks have
        arrived (reports can straddle poll boundaries); a final flush
        after every worker finishes handles ranks that report unevenly.
        """
        world = len(wg.workers)
        pending: Dict[int, Dict[int, dict]] = {}  # iter -> rank -> row

        def process(it: int, rows: Dict[int, dict]) -> None:
            rank0 = rows.get(0) or rows[min(rows)]
            metrics = dict(rank0["metrics"])
            metrics.setdefault("training_iteration", it + 1)
            self._last_metrics = metrics
            self._next_iteration = max(self._next_iteration, it + 1)
            ckpt_path = rank0.get("checkpoint_path")
            if ckpt_path:
                ckpt_mgr.register(Checkpoint(ckpt_path), metrics)
            for cb in self._callbacks:
                handler = getattr(cb, "on_result", None)
                if handler:
                    handler(metrics)
            if self._should_stop(metrics):
                wg.execute("request_stop")

        while True:
            polls = wg.execute("poll")
            for p in polls:
                for r in p["results"]:
                    pending.setdefault(r["iteration"], {})[r["rank"]] = r
            done = all(p["finished"] for p in polls)
            for it in sorted(pending):
                if len(pending[it]) >= world or done:
                    process(it, pending.pop(it))
                else:
                    break  # keep iteration order: wait for stragglers
            errors = [p["error"] for p in polls if p["error"]]
            if errors:
                raise TrainingFailedError(
                    "training worker failed:\n" + errors[0]
                )
            if done:
                return
            time.sleep(_POLL_INTERVAL_S)

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if stop is None:
            return False
        if callable(stop):
            return bool(stop(metrics))
        return any(
            k in metrics and metrics[k] >= v for k, v in stop.items()
        )
