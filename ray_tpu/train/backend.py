"""Backend plugin: per-framework worker-group setup hooks.

Parity: python/ray/train/backend.py:16,32 (Backend/BackendConfig with
on_start/on_training_start/on_shutdown). The reference's _TorchBackend
(train/torch/config.py:36,153) picks worker-0's addr/port and calls
dist.init_process_group on every worker; the TPU-native JaxConfig does
the same handshake with `jax.distributed.initialize` — rank 0 is the
coordinator — then every worker builds the same `jax.sharding.Mesh`
over the gang's chips, and XLA collectives ride ICI from there.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class BackendConfig:
    """Declarative config; backend_cls() yields the imperative hooks."""

    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks run by the controller against the worker group. Each hook
    receives the WorkerGroup and the BackendConfig."""

    share_env_vars: bool = False

    def on_start(self, worker_group, backend_config: "BackendConfig") -> None:
        pass

    def on_training_start(self, worker_group, backend_config: "BackendConfig") -> None:
        pass

    def on_shutdown(self, worker_group, backend_config: "BackendConfig") -> None:
        pass


@dataclass
class JaxConfig(BackendConfig):
    """JAX/TPU backend.

    coordinator_port: for multi-host pods, the jax.distributed
    coordinator (rank 0's host) binds here. mesh_shape: axis sizes for
    the gang's device mesh, e.g. {"data": 2, "model": 4}; defaults to
    pure data-parallel over all chips. enable_distributed: off on a
    single host (one process already owns every local chip — JAX's
    single-controller model needs no rendezvous).
    """

    coordinator_port: int = 0  # 0 = pick a free port on rank 0's host
    mesh_shape: Optional[Dict[str, int]] = None
    enable_distributed: Optional[bool] = None  # None = auto (world_size > 1 hosts)

    @property
    def backend_cls(self):
        return _JaxBackend


def _jax_worker_setup(
    worker, coordinator_addr: str, num_processes: int, process_id: int
):
    """Runs inside each TrainWorker actor: the jax.distributed handshake
    (the _TorchBackend init_process_group analogue)."""
    import jax

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes,
            process_id=process_id,
        )
    return True


def rank0_rendezvous_addr(worker_group, port: int = 0) -> str:
    """host:port every rank can dial, bound on rank 0's host (shared by
    the JAX and Torch backends — the master-addr/port pattern of the
    reference's _TorchBackend, train/torch/config.py:66).

    node_ip, not hostname: simulated hosts have fake hostnames, and
    real pods may not resolve each other's names — the IP the agent
    registered with is what peers can dial."""
    import ray_tpu

    rank0 = worker_group.workers[0]
    if not port:
        port = ray_tpu.get(rank0.actor.pick_free_port.remote())
    ip = rank0.metadata.get("node_ip") or rank0.metadata["hostname"]
    return f"{ip}:{port}"


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        n = len(worker_group.workers)
        distributed = backend_config.enable_distributed
        if distributed is None:
            # distinct hostnames => multi-host gang => rendezvous needed
            hosts = {w.metadata["hostname"] for w in worker_group.workers}
            distributed = len(hosts) > 1
        if not distributed:
            return
        import ray_tpu

        addr = rank0_rendezvous_addr(
            worker_group, backend_config.coordinator_port
        )
        refs = [
            w.actor.run_backend_hook.remote(
                _jax_worker_setup, addr, n, w.rank
            )
            for w in worker_group.workers
        ]
        ray_tpu.get(refs)

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        pass
