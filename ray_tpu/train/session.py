"""Worker-side training session: context, report(), checkpoints.

Parity: python/ray/train/_internal/session.py (_TrainSession :112,
report :405,672) + the public ray.train.get_context()/report() surface.
The session lives inside each TrainWorker actor; ``report`` persists
the checkpoint into the run's storage path and enqueues (metrics,
checkpoint_path) for the controller to drain — the reference's
worker→driver result queue, without Tune in the loop (Train-v2 shape).
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ._checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


@dataclass
class TrainContext:
    """What user train_fns can ask about their world
    (parity: ray.train.get_context() TrainContext)."""

    world_size: int
    world_rank: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    trial_name: str = ""
    trial_id: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id


class _TrainSession:
    def __init__(
        self,
        context: TrainContext,
        storage_dir: str,
        latest_checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[Dict[str, Any]] = None,
        start_iteration: int = 0,
        sync_reports: bool = False,
    ):
        self.context = context
        self.storage_dir = storage_dir
        # sync mode (Tune trials): report() blocks until the controller
        # drains — step-synchronized training, so schedulers (ASHA/PBT)
        # can stop/exploit between iterations (the reference's function
        # trainables block in session.report the same way)
        self.result_queue: "queue.Queue" = queue.Queue(
            maxsize=1 if sync_reports else 0
        )
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        # Continues across gang restarts (controller passes the next
        # global iteration) so checkpoint_NNNNNN dirs never collide with
        # a previous attempt's.
        self.iteration = start_iteration
        self.stop_requested = threading.Event()

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        persisted_path = None
        if checkpoint is not None:
            # rank-0 commits the checkpoint into run storage (the
            # reference's StorageContext.persist_current_checkpoint,
            # train/_internal/storage.py:358); other ranks may report
            # their own shards in multi-host mode — same dir, distinct
            # subpaths, so commits never collide.
            name = f"checkpoint_{self.iteration:06d}"
            if self.context.world_rank == 0:
                dest = os.path.join(self.storage_dir, name)
            else:
                dest = os.path.join(
                    self.storage_dir, name, f"rank_{self.context.world_rank}"
                )
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            persisted_path = os.path.join(self.storage_dir, name)
            self.latest_checkpoint = Checkpoint(persisted_path)
        self.result_queue.put(
            {
                "metrics": dict(metrics),
                "checkpoint_path": persisted_path,
                "iteration": self.iteration,
                "rank": self.context.world_rank,
            }
        )
        self.iteration += 1
        if self.stop_requested.is_set():
            raise StopIteration("training stop requested by controller")


def _init_session(session: "_TrainSession") -> None:
    global _session
    with _session_lock:
        _session = session


def _shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


def _get_session() -> Optional["_TrainSession"]:
    return _session


# ------------------------------------------------------------- public API


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint) from a train_fn
    (parity: ray.train.report, train/_internal/session.py:672)."""
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "ray_tpu.train.report() called outside a training worker"
        )
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        # driver-side default context (world of 1), matching the
        # reference's behavior of degrading gracefully outside training
        return TrainContext(1, 0, 0, 1, 0, "default")
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    """Latest committed checkpoint — how train_fns resume after a
    restart (parity: ray.train.get_checkpoint)."""
    s = _get_session()
    return s.latest_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    """This worker's split of the Dataset passed to the trainer
    (parity: ray.train.get_dataset_shard; reference
    train/_internal/data_config.py:66 streaming_split)."""
    s = _get_session()
    if s is None:
        raise RuntimeError("get_dataset_shard() called outside a training worker")
    return s.dataset_shards.get(name)
