"""Checkpoint: a directory of files, possibly on remote storage.

Parity: python/ray/train/_checkpoint.py (Checkpoint = path + pyarrow
filesystem; as_directory/to_directory/from_directory). TPU-native
extras: ``from_jax`` / ``to_jax`` save & restore a pytree of arrays via
orbax (the ecosystem-standard TPU checkpoint format), with sharded
arrays gathered/scattered against the live mesh on restore.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional

_METADATA_FILE = ".metadata.json"
_PYTREE_FILE = "pytree.msgpack.pkl"


class Checkpoint:
    def __init__(self, path: str, filesystem: Any = None):
        self.path = os.path.abspath(os.path.expanduser(path))
        self.filesystem = filesystem  # pyarrow fs slot; local-only for now

    # ------------------------------------------------------------ basics
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}"
        )
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        # local paths need no materialization
        yield self.path

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        meta = self.get_metadata()
        meta.update(metadata)
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(meta, f)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self):
        return hash(self.path)

    # ------------------------------------------------------- pytree I/O
    @classmethod
    def from_state(cls, state: Any, path: Optional[str] = None) -> "Checkpoint":
        """Persist a picklable object / pytree of host arrays."""
        d = path or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, _PYTREE_FILE), "wb") as f:
            pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
        return cls(d)

    def to_state(self) -> Any:
        with open(os.path.join(self.path, _PYTREE_FILE), "rb") as f:
            return pickle.load(f)

    # --------------------------------------------------------- jax/orbax
    @classmethod
    def from_jax(cls, tree: Any, path: Optional[str] = None) -> "Checkpoint":
        """Save a pytree of jax arrays with orbax (sharding-aware: each
        host writes only its addressable shards on multi-host)."""
        import jax
        import orbax.checkpoint as ocp

        d = os.path.abspath(
            path
            or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        )
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(d, "jax_state"), tree, force=True)
        return cls(d)

    def to_jax(self, target: Any = None, shardings: Any = None) -> Any:
        """Restore the pytree saved by ``from_jax``.

        ``target``: optional pytree template — the restored values are
        re-assembled into its exact structure (dataclasses/TrainState
        included). ``shardings``: optional pytree of
        ``jax.sharding.Sharding`` with the same structure — each
        restored array is placed onto its sharding, so a fresh mesh
        after a gang restart gets correctly-sharded state.
        """
        import jax
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.join(self.path, "jax_state"))
        if target is not None:
            # orbax stores the tree as nested dicts; rebuild the caller's
            # structure (leaf order is preserved by the save/restore pair)
            leaves = jax.tree.leaves(restored)
            treedef = jax.tree.structure(target)
            if treedef.num_leaves != len(leaves):
                raise ValueError(
                    f"checkpoint has {len(leaves)} arrays but target "
                    f"structure expects {treedef.num_leaves}"
                )
            restored = jax.tree.unflatten(treedef, leaves)
        if shardings is not None:
            restored = jax.tree.map(
                lambda x, s: jax.device_put(x, s), restored, shardings
            )
        return restored
