"""Device-mesh construction and axis conventions.

The framework's parallelism surface is expressed as named axes over a
`jax.sharding.Mesh` — the TPU-native replacement for the reference's
process-group world (reference: python/ray/train/torch/config.py:66
builds a torch.distributed NCCL group; python/ray/util/collective/
collective.py:123 builds NCCL groups per device list). On TPU, the mesh
IS the communicator: shardings annotated against these axes make XLA
emit the collectives over ICI.

Axis conventions (every component in the framework uses these names):

- ``data``   — pure data parallelism (batch split; gradients psum).
                Multi-slice/DCN-friendly: keep it the outermost axis.
- ``fsdp``   — data parallelism with parameter sharding (ZeRO-3 /
                fully-sharded): params are sharded on this axis and
                all-gathered by XLA just-in-time; grads reduce-scatter.
- ``model``  — tensor parallelism (Megatron-style sharded matmuls).
- ``seq``    — sequence/context parallelism (ring attention,
                see ray_tpu.ops.ring_attention).
- ``expert`` — expert parallelism for MoE layers.

A mesh does not need all axes: absent axes default to size 1.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order — outermost (slowest-varying, DCN-adjacent) first.
AXIS_ORDER = ("data", "fsdp", "expert", "seq", "model")

# Batch dim of activations is sharded over every data-like axis.
BATCH_AXES = ("data", "fsdp")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. Axes not listed get size 1 and are dropped.

    ``auto_axis`` names the axis that absorbs any unassigned devices
    (device_count // product(explicit sizes)).
    """

    data: int = 1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1
    auto_axis: str = "fsdp"

    def __post_init__(self):
        if self.auto_axis not in AXIS_ORDER:
            raise ValueError(
                f"auto_axis {self.auto_axis!r} not one of {AXIS_ORDER}"
            )

    def sizes(self) -> Dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "expert": self.expert,
            "seq": self.seq,
            "model": self.model,
        }


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all local devices).

    Accepts either a MeshConfig or axis sizes as kwargs:
    ``make_mesh(fsdp=4, model=2)``. If the explicit sizes don't consume
    every device, the remainder goes to ``auto_axis`` (default fsdp) —
    so ``make_mesh()`` on an 8-chip host is an 8-way fsdp mesh.

    All axes in AXIS_ORDER are always present in the mesh (size-1 axes
    included) so PartitionSpecs naming any canonical axis always resolve.
    """
    if config is None:
        config = MeshConfig(**axis_sizes)
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    sizes = config.sizes()
    explicit = math.prod(sizes.values())
    if n % explicit != 0:
        raise ValueError(
            f"{n} devices not divisible by requested mesh {sizes} (={explicit})"
        )
    remainder = n // explicit
    if remainder > 1:
        sizes[config.auto_axis] *= remainder

    shape = tuple(sizes[a] for a in AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    """A 1-device mesh carrying all canonical axes at size 1 — lets
    sharded code paths run unchanged on one chip."""
    dev = device if device is not None else jax.devices()[0]
    return make_mesh(MeshConfig(), devices=[dev])


def batch_spec(extra_dims: int = 1) -> P:
    """PartitionSpec for an activation batch: dim0 over (data, fsdp),
    ``extra_dims`` trailing unsharded dims."""
    return P(BATCH_AXES, *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 1) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def dp_degree(mesh: Mesh) -> int:
    """Total data-parallel degree (batch split factor)."""
    return mesh_axis_size(mesh, "data") * mesh_axis_size(mesh, "fsdp")
