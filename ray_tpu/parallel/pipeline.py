"""Pipeline parallelism: in-program microbatch pipelining over a mesh axis.

The reference gets PP from vLLM/compiled-graphs with NCCL p2p channels
(SURVEY.md §2.5: dag/compiled_dag_node.py:805 +
experimental/channel/torch_tensor_nccl_channel.py:44 — actor pipelines
stitched together at the Python layer). TPU-native PP is the opposite
shape: the WHOLE pipeline is one jitted SPMD program over a `pipe` mesh
axis; stage-to-stage transfer is a single-hop `lax.ppermute` over ICI,
and the schedule is a compile-time loop — no framework in the inner
loop, XLA overlaps each hop with the next microbatch's compute.

Schedule: GPipe-style fill-drain over T = M + P - 1 ticks for M
microbatches on P stages (the classic collective-permute pipeline).
Each device holds ONE stage's params (params stacked on the pipe axis);
at tick t, device p runs its stage on the microbatch that entered at
t - p, then hands the activation to p+1.

Combine with tensor/data axes freely: the stage_fn body may itself use
`model`-axis sharded matmuls; the pipe axis only moves activations.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,  # (M, mb, ...) on THIS device (replicated feed)
    *,
    axis_name: str = "pipe",
) -> jax.Array:
    """Run inside shard_map. ``stage_params`` are the LOCAL stage's
    params; ``microbatches`` is the full (M, ...) input (only stage 0
    consumes it; other stages ignore their copy). Returns (M, ...)
    outputs (only stage P-1's copy is meaningful; the sharded wrapper
    broadcasts it back)."""
    n = jax.lax.psum(1, axis_name)  # static
    idx = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    T = M + n - 1

    state = jnp.zeros(mb_shape, microbatches.dtype)  # current activation
    outputs = jnp.zeros_like(microbatches)

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t (when in range)
        feed_idx = jnp.clip(t, 0, M - 1)
        fed = jnp.where(
            idx == 0,
            microbatches[feed_idx],
            state,
        )
        out = stage_fn(stage_params, fed)
        # last stage records its finished microbatch (entered at t-n+1)
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        is_valid = jnp.logical_and(t - (n - 1) >= 0, t - (n - 1) <= M - 1)
        outputs = jnp.where(
            jnp.logical_and(idx == n - 1, is_valid),
            outputs.at[out_idx].set(out),
            outputs,
        )
        # hand activations downstream: p -> p+1 (last stage's output
        # wraps to 0 but stage 0 overwrites it with the next feed)
        state = jax.lax.ppermute(
            out, axis_name, [(r, (r + 1) % n) for r in range(n)]
        )
        return state, outputs

    state, outputs = jax.lax.fori_loop(0, T, tick, (state, outputs))
    # broadcast final outputs from the last stage to all ranks so the
    # wrapper can declare replicated out_specs
    outputs = jax.lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis_name
    )
    return outputs


def pipeline_train(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    mesh: Mesh,
    *,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    axis_name: str = "pipe",
    microbatch_size: Optional[int] = None,
    schedule: str = "1f1b",
) -> Callable[[jax.Array, jax.Array], Any]:
    """Training pipeline (forward + backward) as ONE jitted SPMD loop.

    schedule="1f1b": one-forward-one-backward — stage p runs forward of
    microbatch m at tick p+m and backward at tick 2(P-1)-p+m, so each
    stage holds at most min(M, 2P-1) stashed activations (the 1F1B
    memory bound; Megatron-LM's non-interleaved schedule).
    schedule="gpipe": all forwards, then all backwards (reverse order) —
    stashes all M activations. Same bubble fraction; 1F1B wins on peak
    activation memory, asserted via compiled memory analysis in tests.

    Backward recomputes the stage forward from the stashed INPUT (remat),
    so only inputs are stored. Returns run(batch, targets) ->
    (mean_loss, stacked_param_grads). The reference has no in-program
    pipeline at all (SURVEY.md §2.5 — PP via NCCL actor pipelines);
    this is the TPU-native shape: lax.ppermute activation/grad hops over
    ICI inside one program.
    """
    if schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown schedule {schedule!r}")
    n_stages = mesh.shape[axis_name]

    def run(batch: jax.Array, targets: jax.Array):
        Btot = batch.shape[0]
        mb = microbatch_size or max(1, Btot // n_stages)
        M = Btot // mb
        micro = batch.reshape(M, mb, *batch.shape[1:])
        tmicro = targets.reshape(M, mb, *targets.shape[1:])
        if schedule == "1f1b":
            K = min(M, 2 * n_stages - 1)
        else:
            K = M

        def body(params_local, micro_local, tmicro_local):
            params = jax.tree.map(lambda p: p[0], params_local)
            n = jax.lax.psum(1, axis_name)
            idx = jax.lax.axis_index(axis_name)
            mb_shape = micro_local.shape[1:]

            # backward tick of microbatch m at stage p
            if schedule == "1f1b":
                def s_bwd(m):
                    return 2 * (n - 1) - idx + m
                T = 2 * (n_stages - 1) + M + 1
            else:
                def s_bwd(m):
                    # reverse order, after the full forward drain
                    return (M - 1 + n - 1) + (n - 1 - idx) + (M - 1 - m)
                T = (M - 1) + (n_stages - 1) + (n_stages - 1) + M + 1

            zero_grads = jax.tree.map(jnp.zeros_like, params)

            def tick(s, carry):
                fwd_in, bwd_in, stash, grad_acc, loss_acc = carry
                # ---- forward slot
                m_f = s - idx
                f_valid = jnp.logical_and(m_f >= 0, m_f < M)
                m_f_c = jnp.clip(m_f, 0, M - 1)
                x_in = jnp.where(idx == 0, micro_local[m_f_c], fwd_in)
                y = stage_fn(params, x_in)
                stash = jnp.where(
                    f_valid,
                    stash.at[m_f_c % K].set(x_in),
                    stash,
                )
                # ---- backward slot (solve s == s_bwd(m) for m)
                if schedule == "1f1b":
                    m_b = s - (2 * (n - 1) - idx)
                else:
                    m_b = (M - 1) - (s - ((M - 1 + n - 1) + (n - 1 - idx)))
                b_valid = jnp.logical_and(m_b >= 0, m_b < M)
                m_b_c = jnp.clip(m_b, 0, M - 1)
                x_saved = stash[m_b_c % K]
                y_b, vjp_fn = jax.vjp(stage_fn, params, x_saved)
                # last stage sources its grad from the loss; others from
                # the downstream hop
                loss_val, dy = jax.value_and_grad(
                    lambda yy: loss_fn(yy, tmicro_local[m_b_c])
                )(y_b)
                g_in = jnp.where(idx == n - 1, dy, bwd_in)
                dparams, dx = vjp_fn(g_in)
                grad_acc = jax.tree.map(
                    lambda acc, g: acc + jnp.where(b_valid, g, 0.0),
                    grad_acc, dparams,
                )
                loss_acc = loss_acc + jnp.where(
                    jnp.logical_and(idx == n - 1, b_valid), loss_val, 0.0
                )
                # ---- hops: activations down (p->p+1), grads up (p->p-1)
                fwd_in = jax.lax.ppermute(
                    y, axis_name, [(r, (r + 1) % n_stages) for r in range(n_stages)]
                )
                bwd_in = jax.lax.ppermute(
                    dx, axis_name, [(r, (r - 1) % n_stages) for r in range(n_stages)]
                )
                return fwd_in, bwd_in, stash, grad_acc, loss_acc

            init = (
                jnp.zeros(mb_shape, micro_local.dtype),
                jnp.zeros(mb_shape, micro_local.dtype),
                jnp.zeros((K, *mb_shape), micro_local.dtype),
                zero_grads,
                jnp.zeros((), jnp.float32),
            )
            _, _, _, grad_acc, loss_acc = jax.lax.fori_loop(0, T, tick, init)
            # mean over microbatches; loss lives on the last stage only
            loss = jax.lax.psum(loss_acc, axis_name) / M
            grads = jax.tree.map(lambda g: (g / M)[None], grad_acc)
            return loss, grads

        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, P(), P()),
            out_specs=(P(), param_specs),
            check_vma=False,
        )(stacked_params, micro, tmicro)

    return run


def pipeline_sharded(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,  # pytree with leading dim P (stacked per stage)
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    microbatch_size: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Build a jit-ready pipelined forward: params' leading dim is
    sharded over ``axis_name`` (one stage per mesh slot); input batch is
    split into microbatches and streamed through the ring."""

    def run(batch: jax.Array) -> jax.Array:
        Btot = batch.shape[0]
        mb = microbatch_size or max(1, Btot // mesh.shape[axis_name])
        M = Btot // mb
        micro = batch.reshape(M, mb, *batch.shape[1:])

        def body(params_local, micro_local):
            # params_local arrives with a leading stage dim of size 1
            params_stage = jax.tree.map(lambda p: p[0], params_local)
            return pipeline_apply(
                stage_fn, params_stage, micro_local, axis_name=axis_name
            )

        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
        out = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, micro)
        return out.reshape(Btot, *out.shape[2:])

    return run
