"""Pipeline parallelism: in-program microbatch pipelining over a mesh axis.

The reference gets PP from vLLM/compiled-graphs with NCCL p2p channels
(SURVEY.md §2.5: dag/compiled_dag_node.py:805 +
experimental/channel/torch_tensor_nccl_channel.py:44 — actor pipelines
stitched together at the Python layer). TPU-native PP is the opposite
shape: the WHOLE pipeline is one jitted SPMD program over a `pipe` mesh
axis; stage-to-stage transfer is a single-hop `lax.ppermute` over ICI,
and the schedule is a compile-time loop — no framework in the inner
loop, XLA overlaps each hop with the next microbatch's compute.

Schedule: GPipe-style fill-drain over T = M + P - 1 ticks for M
microbatches on P stages (the classic collective-permute pipeline).
Each device holds ONE stage's params (params stacked on the pipe axis);
at tick t, device p runs its stage on the microbatch that entered at
t - p, then hands the activation to p+1.

Combine with tensor/data axes freely: the stage_fn body may itself use
`model`-axis sharded matmuls; the pipe axis only moves activations.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,  # (M, mb, ...) on THIS device (replicated feed)
    *,
    axis_name: str = "pipe",
) -> jax.Array:
    """Run inside shard_map. ``stage_params`` are the LOCAL stage's
    params; ``microbatches`` is the full (M, ...) input (only stage 0
    consumes it; other stages ignore their copy). Returns (M, ...)
    outputs (only stage P-1's copy is meaningful; the sharded wrapper
    broadcasts it back)."""
    n = jax.lax.psum(1, axis_name)  # static
    idx = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    T = M + n - 1

    state = jnp.zeros(mb_shape, microbatches.dtype)  # current activation
    outputs = jnp.zeros_like(microbatches)

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t (when in range)
        feed_idx = jnp.clip(t, 0, M - 1)
        fed = jnp.where(
            idx == 0,
            microbatches[feed_idx],
            state,
        )
        out = stage_fn(stage_params, fed)
        # last stage records its finished microbatch (entered at t-n+1)
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        is_valid = jnp.logical_and(t - (n - 1) >= 0, t - (n - 1) <= M - 1)
        outputs = jnp.where(
            jnp.logical_and(idx == n - 1, is_valid),
            outputs.at[out_idx].set(out),
            outputs,
        )
        # hand activations downstream: p -> p+1 (last stage's output
        # wraps to 0 but stage 0 overwrites it with the next feed)
        state = jax.lax.ppermute(
            out, axis_name, [(r, (r + 1) % n) for r in range(n)]
        )
        return state, outputs

    state, outputs = jax.lax.fori_loop(0, T, tick, (state, outputs))
    # broadcast final outputs from the last stage to all ranks so the
    # wrapper can declare replicated out_specs
    outputs = jax.lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis_name
    )
    return outputs


def pipeline_sharded(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,  # pytree with leading dim P (stacked per stage)
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    microbatch_size: Optional[int] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Build a jit-ready pipelined forward: params' leading dim is
    sharded over ``axis_name`` (one stage per mesh slot); input batch is
    split into microbatches and streamed through the ring."""

    def run(batch: jax.Array) -> jax.Array:
        Btot = batch.shape[0]
        mb = microbatch_size or max(1, Btot // mesh.shape[axis_name])
        M = Btot // mb
        micro = batch.reshape(M, mb, *batch.shape[1:])

        def body(params_local, micro_local):
            # params_local arrives with a leading stage dim of size 1
            params_stage = jax.tree.map(lambda p: p[0], params_local)
            return pipeline_apply(
                stage_fn, params_stage, micro_local, axis_name=axis_name
            )

        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
        out = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, micro)
        return out.reshape(Btot, *out.shape[2:])

    return run
